"""Control-plane tests: stores, tenants/limits, service deploy flow, and
the REST webservice end-to-end (aiohttp test client)."""

import asyncio
import io
import json
import zipfile

import pytest
import yaml
from aiohttp.test_utils import TestClient, TestServer

from langstream_tpu.controlplane import (
    ApplicationAlreadyExists,
    ApplicationNotFound,
    ApplicationService,
    FileSystemApplicationStore,
    GlobalMetadataStore,
    InMemoryApplicationStore,
    ResourceLimitExceeded,
    StoredApplication,
    TenantNotFound,
    TenantService,
)
from langstream_tpu.controlplane.codestorage import (
    InMemoryCodeStorage,
    LocalDiskCodeStorage,
)
from langstream_tpu.controlplane.service import LocalExecutor, zip_directory
from langstream_tpu.controlplane.webservice import ControlPlaneWebService

PIPELINE = """
topics:
  - name: input-topic
    creation-mode: create-if-not-exists
  - name: output-topic
    creation-mode: create-if-not-exists
pipeline:
  - name: "upper"
    type: compute
    input: input-topic
    output: output-topic
    configuration:
      fields:
        - name: value.text
          expression: "fn:uppercase(value.text)"
"""

INSTANCE = """
instance:
  streamingCluster:
    type: memory
  computeCluster:
    type: local
"""


def make_app_zip(pipeline=PIPELINE, parallelism=1) -> bytes:
    if parallelism != 1:
        pipeline = pipeline.replace(
            'type: compute',
            f'type: compute\n    resources:\n      parallelism: {parallelism}',
        )
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("pipeline.yaml", pipeline)
    return buf.getvalue()


def make_service(executor=None, tmp_path=None):
    store = (
        FileSystemApplicationStore(str(tmp_path / "apps"))
        if tmp_path is not None
        else InMemoryApplicationStore()
    )
    code = (
        LocalDiskCodeStorage(str(tmp_path / "code"))
        if tmp_path is not None
        else InMemoryCodeStorage()
    )
    tenants = TenantService(GlobalMetadataStore())
    tenants.create("default")
    return ApplicationService(store, code, tenants, executor=executor)


# --------------------------------------------------------------------- #
# stores
# --------------------------------------------------------------------- #
def test_filesystem_store_roundtrip(tmp_path):
    store = FileSystemApplicationStore(str(tmp_path))
    app = StoredApplication(
        application_id="a1", tenant="t", definition={"modules": {}},
        instance={}, secrets={"s": 1},
    )
    store.put(app)
    loaded = store.get("t", "a1")
    assert loaded is not None and loaded.secrets == {"s": 1}
    assert [a.application_id for a in store.list("t")] == ["a1"]
    store.delete("t", "a1")
    assert store.get("t", "a1") is None


def test_public_view_redacts_instance_credentials():
    app = StoredApplication(
        application_id="a", tenant="t", definition={},
        instance={"streamingCluster": {"configuration": {
            "bootstrap": "k:9092", "sasl-password": "hunter2",
        }}},
        secrets={"openai": {"access-key": "k"}},
    )
    view = app.public_view()
    assert "secrets" not in view
    config = view["instance"]["streamingCluster"]["configuration"]
    assert config["sasl-password"] == "***"
    assert config["bootstrap"] == "k:9092"


def test_code_storage_versions(tmp_path):
    storage = LocalDiskCodeStorage(str(tmp_path))
    id1 = storage.store("t", "app", b"v1")
    id2 = storage.store("t", "app", b"v2")
    assert id1 != id2
    assert storage.download("t", id1) == b"v1"
    assert storage.download("t", id2) == b"v2"
    storage.delete("t", id1)
    with pytest.raises(KeyError):
        storage.download("t", id1)


# --------------------------------------------------------------------- #
# tenants + limits
# --------------------------------------------------------------------- #
def test_tenant_crud_and_limits():
    tenants = TenantService(GlobalMetadataStore())
    tenants.create("acme", {"max_total_resource_units": 4})
    assert tenants.get("acme").max_total_resource_units == 4
    tenants.put("acme", {"max_total_resource_units": 2})
    assert tenants.get("acme").max_total_resource_units == 2
    with pytest.raises(TenantNotFound):
        tenants.get("nope")
    tenants.delete("acme")
    assert not tenants.exists("acme")


def test_deploy_respects_resource_limits():
    asyncio.run(_test_deploy_respects_resource_limits())


async def _test_deploy_respects_resource_limits():
    service = make_service()
    service.tenants.put("default", {"max_total_resource_units": 2})
    with pytest.raises(ResourceLimitExceeded):
        await service.deploy(
            "default", "big", make_app_zip(parallelism=3), INSTANCE
        )
    await service.deploy(
        "default", "ok", make_app_zip(parallelism=2), INSTANCE
    )
    # second app would exceed the remaining quota
    with pytest.raises(ResourceLimitExceeded):
        await service.deploy(
            "default", "second", make_app_zip(), INSTANCE
        )


# --------------------------------------------------------------------- #
# service flow
# --------------------------------------------------------------------- #
def test_deploy_get_update_delete():
    asyncio.run(_test_deploy_get_update_delete())


async def _test_deploy_get_update_delete():
    service = make_service()
    stored = await service.deploy("default", "app1", make_app_zip(), INSTANCE)
    assert stored.status == "DEPLOYED"
    assert stored.code_archive_id
    with pytest.raises(ApplicationAlreadyExists):
        await service.deploy("default", "app1", make_app_zip(), INSTANCE)
    updated = await service.deploy(
        "default", "app1", make_app_zip(), INSTANCE, update=True
    )
    assert updated.checksum == stored.checksum
    assert service.download_code("default", "app1") == make_app_zip()
    await service.delete("default", "app1")
    with pytest.raises(ApplicationNotFound):
        service.get("default", "app1")


def test_deploy_validation_failure_does_not_store():
    asyncio.run(_test_deploy_validation_failure_does_not_store())


async def _test_deploy_validation_failure_does_not_store():
    service = make_service()
    bad = io.BytesIO()
    with zipfile.ZipFile(bad, "w") as zf:
        zf.writestr("pipeline.yaml", "pipeline:\n  - name: x\n")  # no type
    with pytest.raises(ValueError):
        await service.deploy("default", "bad", bad.getvalue(), INSTANCE)
    with pytest.raises(ApplicationNotFound):
        service.get("default", "bad")


def test_local_executor_runs_pipeline():
    asyncio.run(_test_local_executor_runs_pipeline())


async def _test_local_executor_runs_pipeline():
    executor = LocalExecutor()
    service = make_service(executor=executor)
    await service.deploy("default", "app1", make_app_zip(), INSTANCE)
    runner = executor.runner("default", "app1")
    assert runner is not None
    producer = runner.producer("input-topic")
    reader = runner.reader("output-topic", position="earliest")
    from langstream_tpu.api.records import Record

    await producer.write(Record(value={"text": "hello"}))
    record = None
    for _ in range(100):
        batch = await reader.read(max_records=1)
        if batch:
            record = batch[0]
            break
        await asyncio.sleep(0.05)
    assert record is not None and record.value["text"] == "HELLO"
    assert any("deployed" in line for line in service.logs("default", "app1"))
    await service.delete("default", "app1")
    assert executor.runner("default", "app1") is None


# --------------------------------------------------------------------- #
# webservice e2e
# --------------------------------------------------------------------- #
def _multipart(archive: bytes):
    import aiohttp

    form = aiohttp.FormData()
    form.add_field("app", archive, filename="app.zip",
                   content_type="application/zip")
    form.add_field("instance", INSTANCE)
    form.add_field("secrets", "secrets: []")
    return form


def test_webservice_end_to_end(tmp_path):
    asyncio.run(_test_webservice_end_to_end(tmp_path))


async def _test_webservice_end_to_end(tmp_path):
    service = make_service(executor=LocalExecutor(), tmp_path=tmp_path)
    ws = ControlPlaneWebService(service)
    client = TestClient(TestServer(ws.app))
    await client.start_server()
    try:
        # tenants
        resp = await client.put("/api/tenants/acme", json={})
        assert resp.status == 200
        resp = await client.get("/api/tenants")
        assert "acme" in await resp.json()

        # deploy
        resp = await client.post(
            "/api/applications/acme/demo", data=_multipart(make_app_zip())
        )
        assert resp.status == 200, await resp.text()
        doc = await resp.json()
        assert doc["status"]["status"] == "DEPLOYED"

        # duplicate → 409
        resp = await client.post(
            "/api/applications/acme/demo", data=_multipart(make_app_zip())
        )
        assert resp.status == 409

        # list + get
        resp = await client.get("/api/applications/acme")
        assert [a["application-id"] for a in await resp.json()] == ["demo"]
        resp = await client.get("/api/applications/acme/demo")
        assert (await resp.json())["checksum"]

        # logs + code download
        resp = await client.get("/api/applications/acme/demo/logs")
        assert "deployed" in await resp.text()
        resp = await client.get("/api/applications/acme/demo/code")
        assert resp.status == 200
        body = await resp.read()
        with zipfile.ZipFile(io.BytesIO(body)) as zf:
            assert "pipeline.yaml" in zf.namelist()

        # unknown tenant → 404
        resp = await client.get("/api/applications/nope")
        assert resp.status == 404

        # delete app, delete tenant
        resp = await client.delete("/api/applications/acme/demo")
        assert resp.status == 200
        resp = await client.delete("/api/tenants/acme")
        assert resp.status == 200
        resp = await client.get("/api/tenants/acme")
        assert resp.status == 404
    finally:
        await client.close()


def test_webservice_auth():
    asyncio.run(_test_webservice_auth())


async def _test_webservice_auth():
    service = make_service()
    ws = ControlPlaneWebService(service, auth_token="sesame")
    client = TestClient(TestServer(ws.app))
    await client.start_server()
    try:
        resp = await client.get("/api/tenants")
        assert resp.status == 401
        resp = await client.get(
            "/api/tenants", headers={"Authorization": "Bearer sesame"}
        )
        assert resp.status == 200
        resp = await client.get("/healthz")
        assert resp.status == 200
    finally:
        await client.close()


def test_archetypes(tmp_path):
    asyncio.run(_test_archetypes(tmp_path))


async def _test_archetypes(tmp_path):
    arch = tmp_path / "archetypes" / "basic"
    arch.mkdir(parents=True)
    (arch / "archetype.yaml").write_text(yaml.safe_dump({
        "archetype": {
            "title": "Basic compute",
            "sections": [{"parameters": [{"name": "greeting"}]}],
        }
    }))
    (arch / "pipeline.yaml").write_text(PIPELINE)
    (arch / "instance.yaml").write_text(INSTANCE)

    service = make_service()
    ws = ControlPlaneWebService(
        service, archetypes_path=str(tmp_path / "archetypes")
    )
    client = TestClient(TestServer(ws.app))
    await client.start_server()
    try:
        resp = await client.get("/api/archetypes/default")
        docs = await resp.json()
        assert docs and docs[0]["id"] == "basic"
        resp = await client.get("/api/archetypes/default/basic")
        assert (await resp.json())["title"] == "Basic compute"
        resp = await client.post(
            "/api/archetypes/default/basic/applications/from-arch",
            json={"greeting": "hi"},
        )
        assert resp.status == 200, await resp.text()
        doc = await resp.json()
        assert doc["application-id"] == "from-arch"
    finally:
        await client.close()


def test_python_agent_workdir_survives_deploy(tmp_path):
    asyncio.run(_test_python_agent_workdir_survives_deploy(tmp_path))


async def _test_python_agent_workdir_survives_deploy(tmp_path):
    """The app's python/ dir must outlive _materialize's temp dir so the
    executor can import user agent code after deploy returns."""
    agent_code = (
        "class Exclaim:\n"
        "    def process(self, record):\n"
        "        return [record.value + '!']\n"
    )
    pipeline = """
topics:
  - name: input-topic
    creation-mode: create-if-not-exists
  - name: output-topic
    creation-mode: create-if-not-exists
pipeline:
  - name: "shout"
    type: python-processor
    input: input-topic
    output: output-topic
    configuration:
      className: shout.Exclaim
"""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("pipeline.yaml", pipeline)
        zf.writestr("python/shout.py", agent_code)
    executor = LocalExecutor()
    service = make_service(executor=executor, tmp_path=tmp_path)
    stored = await service.deploy("default", "pyapp", buf.getvalue(), INSTANCE)
    assert stored.status == "DEPLOYED"
    runner = executor.runner("default", "pyapp")
    from langstream_tpu.api.records import Record

    reader = runner.reader("output-topic", position="earliest")
    await runner.producer("input-topic").write(Record(value="hey"))
    for _ in range(100):
        batch = await reader.read(max_records=1)
        if batch:
            assert batch[0].value == "hey!"
            break
        await asyncio.sleep(0.05)
    else:
        raise AssertionError("no output from python agent")
    await service.delete("default", "pyapp")
