"""Fused ragged paged-attention Pallas kernel (ISSUE 6).

Parity of ``ops/paged_attention.py::ragged_paged_attention`` (interpret
mode — the exact kernel schedule, CPU-verifiable) against the
gather/scatter reference oracle in ``ops/attention.py`` across GQA group
sizes × softcap × sliding window × f32/bf16/int8 pools × ragged lengths
(empty row, single token, exact block boundary, max-table row), plus the
engine-level contract: fused and reference ``paged_kernel`` legs are
token-identical under greedy sampling, and the fused jitted dispatches
contain NO pool-shaped gather — decode, warm prefill-at-offset, and cold
paged prefill all ride the one table-addressed launch path."""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from langstream_tpu.ops.attention import (
    paged_chunk_attention,
    paged_chunk_attention_quant,
    paged_decode_attention,
    paged_decode_attention_quant,
    quantize_kv,
)
from langstream_tpu.ops.paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_quant,
    use_fused_paged,
)

BLOCK = 16


def _paged_layout(k, v, block_size=BLOCK, seed=0, dtype=jnp.float32):
    """Dense [B, T, KVH, D] caches → shuffled block pool + tables, so the
    kernel's table-addressed index maps are tested against NON-identity,
    non-contiguous block placement (same trick as
    tests/test_attention_kernels.py)."""
    batch, max_len, kv_heads, dim = k.shape
    blocks_per_row = max_len // block_size
    total = batch * blocks_per_row
    rng = np.random.RandomState(seed)
    order = rng.permutation(total) + 1  # block 0 stays the null block
    tables = order.reshape(batch, blocks_per_row).astype(np.int32)
    k_pool = np.zeros((total + 1, block_size, kv_heads, dim), np.float32)
    v_pool = np.zeros_like(k_pool)
    for b in range(batch):
        for j in range(blocks_per_row):
            rows = slice(j * block_size, (j + 1) * block_size)
            k_pool[tables[b, j]] = np.asarray(k[b, rows])
            v_pool[tables[b, j]] = np.asarray(v[b, rows])
    return (
        jnp.asarray(k_pool, dtype=dtype),
        jnp.asarray(v_pool, dtype=dtype),
        jnp.asarray(tables),
    )


def _make_cache(batch, max_len, kv_heads, dim, seed=0):
    key = jax.random.PRNGKey(seed)
    kk, kv = jax.random.split(key)
    k = jax.random.normal(kk, (batch, max_len, kv_heads, dim), jnp.float32)
    v = jax.random.normal(kv, (batch, max_len, kv_heads, dim), jnp.float32)
    return k, v


# the ragged-length grid every decode parity case runs: a max-table row
# (every table entry live), a mid-block tail, a single token, and an
# exact block-boundary length
RAGGED_LENGTHS = [64, 17, 1, 32]


# ---------------------------------------------------------------------- #
# decode (Tq=1, start = length-1)
# ---------------------------------------------------------------------- #
@pytest.mark.parametrize("heads,kv_heads", [(4, 4), (4, 2), (8, 2)])
@pytest.mark.parametrize("softcap", [None, 30.0])
def test_fused_decode_matches_reference(heads, kv_heads, softcap):
    batch, max_len, dim = 4, 64, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=1)
    q = jax.random.normal(
        jax.random.PRNGKey(2), (batch, heads, dim), jnp.float32
    )
    lengths = jnp.asarray(RAGGED_LENGTHS, jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v)

    ref = paged_decode_attention(
        q, k_pool, v_pool, tables, lengths, softcap=softcap
    )
    out = ragged_paged_attention(
        q[:, None], k_pool, v_pool, tables, lengths - 1, lengths,
        softcap=softcap, interpret=True,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fused_decode_window_matches_reference():
    batch, max_len, heads, kv_heads, dim = 4, 64, 4, 2, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=3)
    q = jax.random.normal(
        jax.random.PRNGKey(4), (batch, heads, dim), jnp.float32
    )
    lengths = jnp.asarray(RAGGED_LENGTHS, jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, seed=1)
    window = jnp.int32(24)  # narrower than the longest row's context

    ref = paged_decode_attention(
        q, k_pool, v_pool, tables, lengths, window=window
    )
    out = ragged_paged_attention(
        q[:, None], k_pool, v_pool, tables, lengths - 1, lengths,
        window=window, interpret=True,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fused_empty_row_emits_zeros():
    """A row with zero live context (inactive decode slot) is fully
    masked: the fused finalize emits exact zeros — well-defined, unlike
    the reference's fully-masked uniform softmax (both are discarded by
    the engine, but the kernel must not NaN)."""
    batch, max_len, heads, kv_heads, dim = 2, 64, 4, 2, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=5)
    q = jax.random.normal(
        jax.random.PRNGKey(6), (batch, heads, dim), jnp.float32
    )
    lengths = jnp.asarray([0, 40], jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v)
    out = ragged_paged_attention(
        q[:, None], k_pool, v_pool, tables,
        jnp.maximum(lengths - 1, 0), lengths, interpret=True,
    )[:, 0]
    assert np.all(np.isfinite(np.asarray(out)))
    np.testing.assert_array_equal(np.asarray(out[0]), 0.0)
    ref = paged_decode_attention(q, k_pool, v_pool, tables, lengths)
    np.testing.assert_allclose(
        np.asarray(out[1]), np.asarray(ref[1]), rtol=2e-5, atol=2e-5
    )


# ---------------------------------------------------------------------- #
# prefill-at-offset / cold prefill (Tq > 1, ragged starts)
# ---------------------------------------------------------------------- #
@pytest.mark.parametrize("heads,kv_heads", [(4, 4), (4, 2)])
@pytest.mark.parametrize(
    "softcap,window", [(None, None), (30.0, None), (None, 24), (30.0, 24)]
)
def test_fused_chunk_matches_reference(heads, kv_heads, softcap, window):
    """Warm continuation rows at ragged offsets — incl. a cold row
    (start 0) and a row whose suffix is padded (fewer new tokens than
    Tq) — against the gather/scatter chunk reference."""
    batch, seq, max_len, dim = 3, 8, 64, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=7)
    q = jax.random.normal(
        jax.random.PRNGKey(8), (batch, seq, heads, dim), jnp.float32
    )
    starts = jnp.asarray([20, 5, 0], jnp.int32)
    news = [8, 8, 3]  # row 2: padded suffix
    lengths = starts + jnp.asarray(news, jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, seed=2)
    window_arr = None if window is None else jnp.int32(window)

    ref = paged_chunk_attention(
        q, k_pool, v_pool, tables, starts, lengths,
        softcap=softcap, window=window_arr,
    )
    out = ragged_paged_attention(
        q, k_pool, v_pool, tables, starts, lengths,
        softcap=softcap, window=window_arr, interpret=True,
    )
    for b, n in enumerate(news):
        # rows past a row's new-token count are padding garbage in BOTH
        # paths (callers index by length) — compare the live rows
        np.testing.assert_allclose(
            np.asarray(out[b, :n]), np.asarray(ref[b, :n]),
            rtol=2e-5, atol=2e-5,
        )


def test_fused_q_block_tiling_matches_reference():
    """block_q smaller than Tq (multiple q tiles per row, Tq padded to
    the tile) must agree with the single-tile launch and the XLA
    reference."""
    batch, seq, max_len, heads, kv_heads, dim = 2, 10, 64, 4, 2, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=9)
    q = jax.random.normal(
        jax.random.PRNGKey(10), (batch, seq, heads, dim), jnp.float32
    )
    starts = jnp.asarray([16, 0], jnp.int32)
    lengths = starts + jnp.asarray([10, 10], jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, seed=3)
    ref = paged_chunk_attention(q, k_pool, v_pool, tables, starts, lengths)
    out = ragged_paged_attention(
        q, k_pool, v_pool, tables, starts, lengths, block_q=4,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fused_bf16_pool_matches_reference():
    batch, max_len, heads, kv_heads, dim = 2, 64, 4, 2, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=11)
    q = jax.random.normal(
        jax.random.PRNGKey(12), (batch, heads, dim), jnp.float32
    ).astype(jnp.bfloat16)
    lengths = jnp.asarray([60, 33], jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, dtype=jnp.bfloat16)
    ref = paged_decode_attention(q, k_pool, v_pool, tables, lengths)
    out = ragged_paged_attention(
        q[:, None], k_pool, v_pool, tables, lengths - 1, lengths,
        interpret=True,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,  # probs round through bf16 in-kernel
    )


# ---------------------------------------------------------------------- #
# int8 pools (scales stream through the same tables)
# ---------------------------------------------------------------------- #
@pytest.mark.parametrize("heads,kv_heads", [(4, 4), (4, 2), (8, 2)])
@pytest.mark.parametrize("softcap", [None, 30.0])
def test_fused_quant_decode_matches_reference(heads, kv_heads, softcap):
    batch, max_len, dim = 4, 64, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=13)
    q = jax.random.normal(
        jax.random.PRNGKey(14), (batch, heads, dim), jnp.float32
    )
    lengths = jnp.asarray(RAGGED_LENGTHS, jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, seed=4)
    k_q, k_s = quantize_kv(k_pool)
    v_q, v_s = quantize_kv(v_pool)

    ref = paged_decode_attention_quant(
        q, k_q, k_s, v_q, v_s, tables, lengths, softcap=softcap
    )
    out = ragged_paged_attention_quant(
        q[:, None], k_q, k_s, v_q, v_s, tables, lengths - 1, lengths,
        softcap=softcap, interpret=True,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fused_quant_chunk_window_matches_reference():
    batch, seq, max_len, heads, kv_heads, dim = 2, 8, 64, 4, 2, 32
    k, v = _make_cache(batch, max_len, kv_heads, dim, seed=15)
    q = jax.random.normal(
        jax.random.PRNGKey(16), (batch, seq, heads, dim), jnp.float32
    )
    starts = jnp.asarray([24, 0], jnp.int32)
    lengths = starts + jnp.asarray([8, 8], jnp.int32)
    k_pool, v_pool, tables = _paged_layout(k, v, seed=5)
    k_q, k_s = quantize_kv(k_pool)
    v_q, v_s = quantize_kv(v_pool)
    window = jnp.int32(20)

    ref = paged_chunk_attention_quant(
        q, k_q, k_s, v_q, v_s, tables, starts, lengths, window=window
    )
    out = ragged_paged_attention_quant(
        q, k_q, k_s, v_q, v_s, tables, starts, lengths, window=window,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_use_fused_paged_gate():
    # structurally invalid GQA never runs the kernel, interpret or not
    assert not use_fused_paged(128, 5, 2, interpret=True)
    # interpret mode (CPU test hook) accepts any aligned-free shape
    assert use_fused_paged(16, 4, 2, interpret=True)
    # CPU backend, no interpret → gate closed regardless of shape
    assert not use_fused_paged(128, 32, 8)


# ---------------------------------------------------------------------- #
# engine-level: fused vs reference legs, one launch path, no gather
# ---------------------------------------------------------------------- #
def _paged_engine(kernel, kv_quant=None, interpret=True):
    from langstream_tpu.providers.jax_local.engine import DecodeEngine
    from langstream_tpu.providers.jax_local.model import (
        LlamaConfig,
        init_params,
    )

    config = LlamaConfig.tiny(max_seq_len=128)
    if interpret:
        # the CPU hook: _use_fused_paged runs the kernel in Pallas
        # interpret mode instead of falling back to the reference
        config = dataclasses.replace(config, flash_interpret=True)
    params = init_params(config)
    return DecodeEngine(
        config, params, max_slots=4, max_seq_len=128,
        prefill_buckets=[16, 32, 64], kv_quant=kv_quant,
        kv_layout="paged", kv_block_size=8, paged_kernel=kernel,
    )


@pytest.mark.parametrize(
    "kv_quant",
    [
        # int8 is the tier-1 representative (covers the scale-folded
        # quant path on top of everything bf16 exercises); the bf16
        # leg rides the slow tier — each leg builds two engines (~15s)
        pytest.param(None, marks=pytest.mark.slow),
        "int8",
    ],
)
def test_engine_fused_matches_reference_greedy(kv_quant):
    """Token-identical greedy output across the kernel A/B legs — cold
    prefill, warm prefix-hit continuation, and decode all dispatch
    through the fused launch on one leg and the gather oracle on the
    other."""
    from langstream_tpu.providers.jax_local.engine import SamplingParams

    async def run(engine):
        first = await engine.generate(
            list(range(1, 40)), SamplingParams(max_new_tokens=6)
        )
        # shares blocks with the first prompt → prefix-hit admission
        # exercises the warm prefill-at-offset path
        second = await engine.generate(
            list(range(1, 33)) + [99, 98], SamplingParams(max_new_tokens=6)
        )
        return first.tokens, second.tokens

    fused = _paged_engine("fused", kv_quant=kv_quant)
    reference = _paged_engine("reference", kv_quant=kv_quant,
                              interpret=False)
    fused.start()
    reference.start()
    try:
        assert fused.cost_model.paged_kernel == "fused"
        assert reference.cost_model.paged_kernel == "reference"
        assert asyncio.run(run(fused)) == asyncio.run(run(reference))
        # the fused leg actually served traffic through the prefix pool
        assert fused.kv_manager.stats["hit_tokens"] >= 32
    finally:
        fused.stop()
        reference.stop()


def _pool_gather_lines(engine, text):
    """Shared HLO rule helpers (langstream_tpu/analysis/hlo_lint.py):
    lines gathering the per-layer pool [N, Bs, KVH, D] — the signature
    of the reference's materialized ``gather_blocks`` copy. Other
    gathers (embedding lookup, table row lookup) have different operand
    shapes and don't count."""
    from langstream_tpu.analysis.hlo_lint import (
        pool_dims,
        pool_gather_lines,
    )

    return pool_gather_lines(text, pool_dims(engine))


def test_fused_dispatches_contain_no_pool_gather():
    """The acceptance check for 'one fused launch, no per-path gather':
    decode, warm prefill-at-offset, AND cold paged prefill lower without
    a single pool-shaped gather on the fused leg, while every reference
    dispatch carries them (k and v per layer scan)."""
    from langstream_tpu.analysis.hlo_lint import lowered_text

    fused = _paged_engine("fused")
    reference = _paged_engine("reference", interpret=False)
    try:
        for engine in (fused, reference):
            variants = {
                "decode": engine._get_decode(1),
                "cold_prefill": engine._get_prefill(16),
                "prefill_offset": engine._get_prefill_offset(16),
            }
            for name, fn in variants.items():
                lines = _pool_gather_lines(engine, lowered_text(engine, fn))
                if engine is fused:
                    assert not lines, (
                        f"fused {name} still gathers the pool:\n"
                        + "\n".join(lines[:4])
                    )
                elif name == "cold_prefill":
                    # reference cold prefill runs the dense layer scan —
                    # cold self-attention never READS the cache, so no
                    # pool gather to lose
                    continue
                else:
                    assert lines, f"reference {name} lost its gather"
    finally:
        fused.stop()
        reference.stop()


def test_engine_rejects_unknown_paged_kernel():
    with pytest.raises(ValueError, match="paged kernel"):
        _paged_engine("turbo")


def test_engine_resolves_fused_fallback_to_reference():
    """A requested fused kernel the model gate rejects (CPU backend, no
    interpret hook) resolves to reference AT ENGINE INIT: the
    kernel-aware byte model and flight/artifact telemetry must charge
    the gather path that actually runs — a silent fused→reference
    fallback that kept the fused label would read MBU ~3x low."""
    engine = _paged_engine("fused", interpret=False)
    try:
        assert engine.paged_kernel_requested == "fused"
        assert engine.paged_kernel == "reference"
        assert engine.cost_model.paged_kernel == "reference"
    finally:
        engine.stop()

    # interpret hook open → the request sticks
    fused = _paged_engine("fused", interpret=True)
    try:
        assert fused.paged_kernel == "fused"
        assert fused.paged_kernel_requested == "fused"
    finally:
        fused.stop()


def test_provider_plumbs_paged_kernel():
    """engine: {paged-kernel: ...} flows compiler globals → provider →
    engine (string-coerced like every other engine knob)."""
    from langstream_tpu.providers.jax_local.provider import (
        JaxCompletionsService,
    )

    service = JaxCompletionsService({
        "model": {"preset": "tiny"},
        "engine": {
            "max-slots": "2", "max-seq-len": "64",
            "kv-layout": "paged", "kv-block-size": "8",
            "paged-kernel": "reference",
        },
    })
    try:
        assert service.engine.paged_kernel == "reference"
        assert service.engine.cost_model.paged_kernel == "reference"
    finally:
        service.engine.stop()
