"""Two-OS-process mirror: the leader engine in THIS process, a follower
in a REAL child process, connected over localhost TCP — the deployment
shape of multi-host SPMD serving (each host is its own OS process on
its own mesh; no jax.distributed needed for the contract itself).

SURVEY §7 hard part (e); round-3 verdict weak #4: the single-process
test proved replay algebra, not the transport + process separation.
Asserts: fuzzed traffic replays to a bit-identical device state across
the process boundary, and a follower with a mismatched serving-config
fingerprint is rejected at handshake while a correct one still joins.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import threading


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mirror_follower_worker.py")


def _spawn_follower(
    port: int, out_path: str, fingerprint: bytes, kind: str = "dense"
):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, WORKER, "127.0.0.1", str(port), out_path,
            fingerprint.hex(), kind,
        ],
        env=env,
    )


def test_two_process_replay_token_identical(tmp_path):
    from langstream_tpu.providers.jax_local.engine import (
        DecodeEngine,
        SamplingParams,
    )
    from langstream_tpu.providers.jax_local.model import (
        LlamaConfig,
        init_params,
    )
    from langstream_tpu.serving.mirror import (
        DispatchMirror,
        config_fingerprint,
    )

    from tests.mirror_follower_worker import state_digest

    fingerprint = config_fingerprint({"model": "tiny-twoproc"})
    config = LlamaConfig.tiny(max_seq_len=256)
    leader = DecodeEngine(
        config, init_params(config), max_slots=3, max_seq_len=256,
        prefill_buckets=[16, 32], decode_chunk=4, pipeline_decode=True,
    )
    mirror = DispatchMirror(
        host="127.0.0.1", port=0, fingerprint=fingerprint
    )
    out_path = str(tmp_path / "follower.json")
    follower = _spawn_follower(mirror.port, out_path, fingerprint)
    try:
        mirror.wait_for_followers(1, timeout=180)
        leader.mirror = mirror
        leader.start()

        import random

        rng = random.Random(20260730)
        template = [(17 * j) % 250 + 1 for j in range(24)]

        def prompt(i):
            if i % 3 == 0:  # shared template -> cross-slot prefix copies
                return template + [(i * 7 + j) % 250 + 1 for j in range(3)]
            if i % 3 == 1:  # longer than the largest bucket -> chunked
                return [(i * 13 + j) % 250 + 1 for j in range(50)]
            return [(i * 11 + j) % 250 + 1 for j in range(10)]

        async def drive():
            async def late(i):
                await asyncio.sleep(0.003 * rng.randrange(5))
                return await leader.generate(
                    prompt(i),
                    SamplingParams(
                        max_new_tokens=rng.randrange(3, 7),
                        temperature=rng.choice([0.0, 0.8]),
                        seed=i,
                    ),
                    session_id=f"s{i % 2}" if i % 3 == 2 else None,
                )

            return await asyncio.gather(*[late(i) for i in range(9)])

        results = asyncio.run(drive())
        assert all(r.tokens for r in results)
    finally:
        leader.stop()  # publishes the stop record and closes the mirror
    assert follower.wait(timeout=300) == 0
    with open(out_path) as handle:
        report = json.load(handle)
    assert report["records"] > 0
    # bit-identical device state across a real process boundary —
    # cache bits encode the full decode history, so this is
    # token-identical replay
    assert report["digest"] == state_digest(leader)


def test_two_process_paged_replay_token_identical(tmp_path):
    """The ISSUE 8 mirror acceptance: leader + one follower in a REAL
    child process with ``kv_layout: paged`` replay to bitwise-identical
    device state — through a ≥256-token shared-prefix hit, a mid-block
    COW divergence, chunked long prefill, and pool-pressure eviction.
    Paged dispatch records carry their block-table rows and COW copies
    their own records; the follower never runs the block allocator."""
    from langstream_tpu.providers.jax_local.engine import (
        DecodeEngine,
        SamplingParams,
    )
    from langstream_tpu.providers.jax_local.model import (
        LlamaConfig,
        init_params,
    )
    from langstream_tpu.serving.mirror import (
        DispatchMirror,
        config_fingerprint,
    )

    from tests.mirror_follower_worker import state_digest

    fingerprint = config_fingerprint({"model": "tiny-twoproc-paged"})
    config = LlamaConfig.tiny(max_seq_len=512)
    # same shape as mirror_follower_worker.build_engine("paged")
    leader = DecodeEngine(
        config, init_params(config), max_slots=3, max_seq_len=512,
        prefill_buckets=[16, 32, 64, 256], decode_chunk=4,
        kv_layout="paged", kv_block_size=16, kv_blocks=40,
    )
    mirror = DispatchMirror(
        host="127.0.0.1", port=0, fingerprint=fingerprint
    )
    out_path = str(tmp_path / "follower_paged.json")
    follower = _spawn_follower(mirror.port, out_path, fingerprint, "paged")
    try:
        mirror.wait_for_followers(1, timeout=180)
        leader.mirror = mirror
        leader.start()

        template = [(17 * j) % 250 + 1 for j in range(256)]

        async def drive():
            # chunked cold prefill (258 > largest bucket) publishing a
            # 256-token prefix chain under a session id
            r1 = await leader.generate(
                template + [7, 8], SamplingParams(max_new_tokens=4),
                session_id="cow",
            )
            # ≥256-token shared-prefix hit (block-granular admission)
            await leader.generate(
                template + [9, 10, 11], SamplingParams(max_new_tokens=4)
            )
            # session follow-up diverging mid-block inside the
            # published prefix → COW block copy record
            history = template + [7, 8] + r1.tokens
            follow = history[:133] + [201, 202, 203]
            await leader.generate(
                follow, SamplingParams(max_new_tokens=4),
                session_id="cow",
            )
            # distinct prompts exhaust the 40-block pool → eviction
            for i in range(4):
                await leader.generate(
                    [(i * 31 + j) % 250 + 1 for j in range(120)],
                    SamplingParams(max_new_tokens=4),
                )

        asyncio.run(drive())
        stats = leader.kv_manager.stats
        assert stats["hit_tokens"] >= 256, stats
        assert stats["cow_copies"] >= 1, stats
        assert stats["evictions"] >= 1, stats
    finally:
        leader.stop()  # publishes the stop record and closes the mirror
    assert follower.wait(timeout=300) == 0
    with open(out_path) as handle:
        report = json.load(handle)
    assert report["records"] > 0
    # bitwise-identical pool + counts across the process boundary:
    # cache bits encode the full decode history, so this is
    # token-identical replay of the paged protocol
    assert report["digest"] == state_digest(leader)


def test_two_process_fingerprint_mismatch_rejected(tmp_path):
    from langstream_tpu.serving.mirror import (
        DispatchMirror,
        config_fingerprint,
    )

    leader_fp = config_fingerprint({"engine": {"max-slots": 4}})
    wrong_fp = config_fingerprint({"engine": {"max-slots": 8}})
    mirror = DispatchMirror(host="127.0.0.1", port=0, fingerprint=leader_fp)
    accepted = threading.Event()

    def waiter():
        mirror.wait_for_followers(1, timeout=120)
        accepted.set()

    thread = threading.Thread(target=waiter)
    thread.start()
    try:
        bad_out = str(tmp_path / "bad.json")
        bad = _spawn_follower(mirror.port, bad_out, wrong_fp)
        # rejected at handshake: the worker sees its socket close before
        # any record and exits 3; the leader keeps waiting
        assert bad.wait(timeout=120) == 3
        assert not accepted.is_set()

        good = _spawn_follower(
            mirror.port, str(tmp_path / "good.json"), leader_fp
        )
        try:
            assert accepted.wait(timeout=120)
        finally:
            mirror.close()  # stream close -> follower run() returns
            good.wait(timeout=60)
    finally:
        thread.join(timeout=10)
        mirror.close()
