"""Kubernetes REST facade over :class:`MockKubeApi` for integration tests.

The reference tests its k8s path against a fabric8 mock API server
(``KubeTestServer.java:46``); this is the same idea: the real HTTP client
(``deployer/kubeclient.py``) exercises create/replace/list/delete/patch
semantics against an in-memory object store.
"""

from __future__ import annotations

import json
from typing import Optional

from aiohttp import web

from langstream_tpu.deployer.kube import MockKubeApi
from langstream_tpu.deployer.kubeclient import _KIND_ROUTES

_PLURAL_TO_KIND = {
    plural: kind for kind, (_prefix, plural) in _KIND_ROUTES.items()
}


class MockKubeRestServer:
    """Serves the subset of the Kubernetes REST API the client uses."""

    def __init__(self, kube: Optional[MockKubeApi] = None) -> None:
        self.kube = kube or MockKubeApi()
        self._runner = None
        self.port: Optional[int] = None

    async def start(self) -> int:
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        self._runner = runner
        self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def _dispatch(self, request: web.Request) -> web.Response:
        # path shapes:
        #   /api/v1/namespaces/{ns}/{plural}[/{name}[/status]]
        #   /apis/{group}/{version}/namespaces/{ns}/{plural}[/{name}[/status]]
        #   /apis/{group}/{version}/{plural}  (cluster-scoped / all-ns list)
        parts = [p for p in request.path.split("/") if p]
        namespace = None
        if "namespaces" in parts:
            idx = parts.index("namespaces")
            namespace = parts[idx + 1]
            rest = parts[idx + 2:]
        elif parts[0] == "api":
            rest = parts[2:]
        else:  # apis/{group}/{version}/...
            rest = parts[3:]
        if not rest:
            return web.json_response({"message": "bad path"}, status=400)
        plural = rest[0]
        name = rest[1] if len(rest) > 1 else None
        subresource = rest[2] if len(rest) > 2 else None
        kind = _PLURAL_TO_KIND.get(plural)
        if kind is None:
            return web.json_response(
                {"message": f"unknown resource {plural}"}, status=404
            )
        ns = namespace or "default"

        if request.method == "GET" and name:
            doc = self.kube.get(kind, ns, name)
            if doc is None:
                return web.json_response({"message": "not found"}, status=404)
            return web.json_response(doc)
        if request.method == "GET":
            selector = request.query.get("labelSelector")
            labels = None
            if selector:
                labels = dict(
                    pair.split("=", 1) for pair in selector.split(",")
                )
            items = self.kube.list(
                kind, namespace if namespace else None, labels
            )
            return web.json_response({"items": items})
        if request.method == "POST":
            doc = json.loads(await request.read())
            key_name = doc.get("metadata", {}).get("name")
            if self.kube.get(kind, ns, key_name) is not None:
                return web.json_response(
                    {"message": "already exists", "reason": "AlreadyExists"},
                    status=409,
                )
            doc.setdefault("metadata", {}).setdefault("namespace", ns)
            doc.setdefault("kind", kind)
            return web.json_response(self.kube.apply(doc), status=201)
        if request.method == "PUT" and name:
            doc = json.loads(await request.read())
            doc.setdefault("metadata", {}).setdefault("namespace", ns)
            doc.setdefault("kind", kind)
            return web.json_response(self.kube.apply(doc))
        if request.method == "PATCH" and name and subresource == "status":
            body = json.loads(await request.read())
            doc = self.kube.patch_status(
                kind, ns, name, body.get("status", {})
            )
            if doc is None:
                return web.json_response({"message": "not found"}, status=404)
            return web.json_response(doc)
        if request.method == "DELETE" and name:
            if not self.kube.delete(kind, ns, name):
                return web.json_response({"message": "not found"}, status=404)
            return web.json_response({"status": "Success"})
        return web.json_response({"message": "unsupported"}, status=405)
