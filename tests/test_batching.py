import asyncio

from langstream_tpu.runtime.batching import BatchExecutor, OrderedAsyncBatchExecutor


def run(coro):
    return asyncio.run(coro)


def test_batch_executor_flush_on_size():
    async def main():
        batches = []

        async def proc(batch):
            batches.append(list(batch))

        ex = BatchExecutor(3, proc)
        for i in range(7):
            await ex.add(i)
        assert batches == [[0, 1, 2], [3, 4, 5]]
        await ex.close()
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]

    run(main())


def test_batch_executor_flush_on_timer():
    async def main():
        batches = []

        async def proc(batch):
            batches.append(list(batch))

        ex = BatchExecutor(100, proc, flush_interval=0.05)
        await ex.add("a")
        await asyncio.sleep(0.15)
        assert batches == [["a"]]

    run(main())


def test_batch_executor_flush_on_bytes():
    async def main():
        batches = []

        async def proc(batch):
            batches.append(list(batch))

        ex = BatchExecutor(
            100, proc, max_bytes=10, size_of=len
        )
        await ex.add("aaaa")
        await ex.add("bbbbbbb")  # 11 bytes total -> flush
        assert batches == [["aaaa", "bbbbbbb"]]

    run(main())


def test_ordered_executor_preserves_per_key_order():
    async def main():
        processed = []

        async def proc(batch):
            # simulate variable async latency: later batches finish "faster"
            await asyncio.sleep(0.01)
            processed.extend(batch)

        ex = OrderedAsyncBatchExecutor(
            2,
            proc,
            buckets=4,
            hash_fn=lambda item: hash(item[0]),
        )
        items = [("k1", i) for i in range(6)] + [("k2", i) for i in range(6)]
        for item in items:
            await ex.add(item)
        await ex.close()

        k1 = [v for k, v in processed if k == "k1"]
        k2 = [v for k, v in processed if k == "k2"]
        assert k1 == list(range(6))
        assert k2 == list(range(6))

    run(main())


def test_ordered_executor_single_inflight_per_bucket():
    async def main():
        inflight = {"now": 0, "max": 0}

        async def proc(batch):
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
            await asyncio.sleep(0.02)
            inflight["now"] -= 1

        ex = OrderedAsyncBatchExecutor(
            1, proc, buckets=1, hash_fn=lambda item: 0
        )
        for i in range(5):
            await ex.add(i)
        await ex.close()
        assert inflight["max"] == 1  # order within bucket => serialized

    run(main())


def test_ordered_executor_parallel_across_buckets():
    async def main():
        inflight = {"now": 0, "max": 0}

        async def proc(batch):
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
            await asyncio.sleep(0.05)
            inflight["now"] -= 1

        # deterministic bucket spread: with salted hash() there is a
        # ~1.6% chance all four keys collide into one bucket, where
        # serial processing is CORRECT and the overlap assert misfires
        ex = OrderedAsyncBatchExecutor(
            1, proc, buckets=4, hash_fn=lambda key: int(key.split("-")[1])
        )
        for i in range(4):
            await ex.add(f"key-{i}")
        await ex.close()
        assert inflight["max"] > 1  # different buckets overlap

    run(main())
