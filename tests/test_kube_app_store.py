"""Applications stored AS custom resources (reference:
KubernetesApplicationStore.java:66): round trip through the kube verb
interface, secrets in a sibling Secret, status read back from the CR."""

from __future__ import annotations

from langstream_tpu.controlplane import (
    KubernetesApplicationStore,
    StoredApplication,
)
from langstream_tpu.deployer.kube import MockKubeApi


def _app(app_id="a1", tenant="team-a"):
    return StoredApplication(
        application_id=app_id,
        tenant=tenant,
        definition={"modules": {"default": {"pipelines": {}, "topics": {}}}},
        instance={"streaming_cluster": {"type": "memory"}},
        secrets={"open-ai": {"access-key": "sk-secret"}},
        code_archive_id="a1-abc",
        checksum="c0ffee",
    )


def test_roundtrip_and_secret_separation():
    kube = MockKubeApi()
    store = KubernetesApplicationStore(kube)
    store.put(_app())

    # the app document is a CR; secrets live in a separate k8s Secret
    cr = kube.get("Application", "team-a", "a1")
    assert cr is not None
    assert "sk-secret" not in str(cr)
    secret = kube.get("Secret", "team-a", "langstream-app-a1")
    assert secret is not None

    loaded = store.get("team-a", "a1")
    assert loaded.definition["modules"]
    assert loaded.secrets == {"open-ai": {"access-key": "sk-secret"}}
    assert loaded.code_archive_id == "a1-abc"
    assert loaded.checksum == "c0ffee"

    # status flows back from the CR (what the operator patches)
    kube.patch_status(
        "Application", "team-a", "a1",
        {"phase": "DEPLOYED", "detail": "ok"},
    )
    assert store.get("team-a", "a1").status == "DEPLOYED"

    assert [a.application_id for a in store.list("team-a")] == ["a1"]
    store.delete("team-a", "a1")
    assert store.get("team-a", "a1") is None
    assert kube.get("Secret", "team-a", "langstream-app-a1") is None


def test_tenant_cleanup():
    kube = MockKubeApi()
    store = KubernetesApplicationStore(kube)
    store.put(_app("a1"))
    store.put(_app("a2"))
    store.put(_app("other", tenant="team-b"))
    store.on_tenant_deleted("team-a")
    assert store.list("team-a") == []
    assert [a.application_id for a in store.list("team-b")] == ["other"]


def test_configmap_metadata_store_and_tenants():
    from langstream_tpu.controlplane import (
        KubernetesGlobalMetadataStore,
        TenantService,
    )

    kube = MockKubeApi()
    store = KubernetesGlobalMetadataStore(kube, namespace="langstream")
    store.put("k1", {"a": 1})
    assert store.get("k1") == {"a": 1}
    assert store.keys() == ["k1"]
    # persisted through the cluster: a new store instance sees it
    assert KubernetesGlobalMetadataStore(
        kube, namespace="langstream"
    ).get("k1") == {"a": 1}
    store.delete("k1")
    assert store.keys() == []

    # the tenant registry rides it unchanged
    tenants = TenantService(store)
    tenants.create("team-a")
    assert "team-a" in {t.name for t in tenants.list()}
