import pytest

from langstream_tpu.agents.el import (
    ExpressionError,
    evaluate,
    evaluate_predicate,
    render_template,
)


CTX = {
    "value": {"question": "what is jax?", "count": 3, "nested": {"deep": "yes"}},
    "key": "k1",
    "properties": {"lang": "en"},
    "timestamp": 1000,
}


def test_field_access():
    assert evaluate("value.question", CTX) == "what is jax?"
    assert evaluate("value.nested.deep", CTX) == "yes"
    assert evaluate("key", CTX) == "k1"
    assert evaluate("properties['lang']", CTX) == "en"
    assert evaluate("value.missing", CTX) is None


def test_operators_and_predicates():
    assert evaluate("value.count + 1", CTX) == 4
    assert evaluate_predicate("value.count > 2", CTX)
    assert not evaluate_predicate("value.count > 5", CTX)
    assert evaluate_predicate("value.question == 'what is jax?'", CTX)
    assert evaluate("'yes' if value.count > 1 else 'no'", CTX) == "yes"


def test_fn_namespace():
    assert evaluate("fn.uppercase(value.question)", CTX) == "WHAT IS JAX?"
    assert evaluate("fn.concat(key, '-', properties['lang'])", CTX) == "k1-en"
    assert evaluate("fn.coalesce(value.missing, 'dflt')", CTX) == "dflt"
    assert evaluate("fn.len(value.question)", CTX) == 12
    assert evaluate("fn.split('a,b,c', ',')", CTX) == ["a", "b", "c"]
    assert evaluate("fn.toInt('42')", CTX) == 42
    assert evaluate("fn.timestampAdd(timestamp, 1, 'seconds')", CTX) == 2000


def test_jstl_colon_syntax_accepted():
    assert evaluate("fn:uppercase(value.question)", CTX) == "WHAT IS JAX?"
    assert evaluate("${value.count + 1}", CTX) == 4


def test_sandbox_blocks_dangerous_code():
    for bad in [
        "__import__('os').system('true')",
        "().__class__.__bases__",
        "open('/etc/passwd')",
        "exec('x=1')",
        "lambda: 1",
        "[x for x in value]",
    ]:
        with pytest.raises(ExpressionError):
            evaluate(bad, CTX)


def test_safe_builtins_allowed():
    assert evaluate("len(value.question)", CTX) == 12
    assert evaluate("max(1, value.count)", CTX) == 3
    assert evaluate("str(value.count)", CTX) == "3"


def test_render_template():
    out = render_template(
        "Q: {{ value.question }} ({{ properties['lang'] }})", CTX
    )
    assert out == "Q: what is jax? (en)"
    assert render_template("{{ value.missing }}", CTX) == ""
    assert render_template("{{{ value.question }}}", CTX) == "what is jax?"
    # dict values render as JSON
    assert render_template("{{ value.nested }}", CTX) == '{"deep": "yes"}'


def test_error_messages():
    with pytest.raises(ExpressionError, match="bad expression"):
        evaluate("value..", CTX)
