"""Self-healing serving (ISSUE 9): deterministic fault injection +
engine supervisor with bitwise session resurrection.

The acceptance arc: with ``LANGSTREAM_FAULTS=engine_thread_crash@step=N``
armed, a session killed mid-decode resumes on a rebuilt engine and its
FULL output is bitwise identical to the same request on an uncrashed
engine (greedy and seeded stochastic — penalties included — on dense and
paged layouts, spec-on too), no other in-flight session is failed (zero
500s; only bounded 503 + Retry-After during the rebuild), and the
recovery leaves evidence on every plane: ``engine_restarts_total`` /
``sessions_resurrected_total`` / ``engine_recovery_seconds`` in the
engine snapshot, ``engine_recovery`` flight events, an
``engine.recovery`` trace span, and ``tokens_wasted{crash_replay}`` in
the goodput ledger. Satellites: admission-deadline load shedding,
watchdog escalation, the paged-allocator and dispatch fault points, and
the OpenAI surface's sibling-cancellation error propagation."""

import asyncio
import time

import pytest

from langstream_tpu.api import errors as api_errors
from langstream_tpu.providers.jax_local.engine import (
    DecodeEngine,
    SamplingParams,
    engines_histograms,
    engines_snapshot,
)
from langstream_tpu.providers.jax_local.model import LlamaConfig, init_params
from langstream_tpu.runtime import faults
from langstream_tpu.runtime.supervisor import EngineSupervisor


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends disarmed with zeroed arrival counters
    (the registry is process-global by design — a one-shot fault stays
    consumed across a supervisor rebuild)."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def flight_recorder(tmp_path):
    from langstream_tpu.runtime import flight

    saved = flight.RECORDER.path
    flight.RECORDER.path = None
    flight.RECORDER._pending.clear()
    path = flight.configure(str(tmp_path / "flight"))
    yield flight, path
    flight.RECORDER.flush()
    flight.RECORDER.path = saved


# ---------------------------------------------------------------------- #
# fault registry (runtime/faults.py)
# ---------------------------------------------------------------------- #
def test_fault_spec_parsing_and_describe():
    specs = faults.parse_spec(
        "engine_thread_crash@step=40,dispatch_error@step=7:1.0,"
        "stuck_step@step=5;dur=45"
    )
    assert [s.point for s in specs] == [
        "engine_thread_crash", "dispatch_error", "stuck_step",
    ]
    assert specs[0].step == 40 and specs[0].prob is None
    assert specs[1].prob == 1.0
    assert specs[2].params == {"dur": "45"}
    assert specs[2].describe() == "stuck_step@step=5;dur=45"
    for bad in ("nope", "x@stop=3", "x@step=abc", "x@step=1:1.5"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_one_shot_fires_exactly_once():
    faults.configure("p@step=3")
    fired = [bool(faults.fire("p")) for _ in range(6)]
    assert fired == [False, False, True, False, False, False]
    # a rebuilt engine re-passing the point does NOT re-fire: arrival
    # counters are process-global for the registry's lifetime
    with pytest.raises(faults.InjectedFault):
        faults.configure("q@step=1")
        faults.check("q")
    faults.check("q")  # consumed


def test_probabilistic_faults_are_deterministic():
    faults.configure("p@step=2:0.5", seed=7)
    first = [bool(faults.fire("p")) for _ in range(64)]
    assert not first[0]  # armed only from step 2
    assert any(first) and not all(first)
    faults.reset()
    faults.configure("p@step=2:0.5", seed=7)
    assert [bool(faults.fire("p")) for _ in range(64)] == first
    faults.reset()
    faults.configure("p@step=2:1.0", seed=7)
    assert [bool(faults.fire("p")) for _ in range(4)] == [
        False, True, True, True,
    ]


def test_unarmed_registry_is_inert_and_cheap():
    assert not faults.armed()
    assert faults.fire("anything") is None
    faults.check("anything")  # no raise
    assert faults.maybe_sleep("anything") == 0.0


def test_stuck_step_sleeps_for_configured_duration():
    faults.configure("stuck_step@step=1;dur=0.05")
    started = time.perf_counter()
    slept = faults.maybe_sleep("stuck_step")
    assert slept == pytest.approx(0.05)
    assert time.perf_counter() - started >= 0.04


def test_pool_exhausted_fault_point():
    from langstream_tpu.providers.jax_local.paged import PagedKVManager

    manager = PagedKVManager(num_blocks=8, block_size=4)
    faults.configure("pool_exhausted@step=1")
    assert manager.allocate(2) is None  # injected exhaustion, no state
    fresh = manager.allocate(2)         # one-shot consumed
    assert fresh is not None and len(fresh) == 2


def test_env_arming(monkeypatch):
    monkeypatch.setenv("LANGSTREAM_FAULTS", "engine_thread_crash@step=9")
    faults.configure_from_env()
    assert faults.armed()
    assert "engine_thread_crash" in faults.REGISTRY.describe()


# ---------------------------------------------------------------------- #
# crash → rebuild → bitwise resurrection
# ---------------------------------------------------------------------- #
def _factory(config, params, **overrides):
    kwargs = dict(
        max_slots=4, max_seq_len=128, prefill_buckets=[16, 32],
        decode_chunk=4, seed=11,
    )
    kwargs.update(overrides)
    return lambda: DecodeEngine(config, params, **kwargs)


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(max_seq_len=512)
    return config, init_params(config)


GREEDY = dict(max_new_tokens=20)
SEEDED = dict(
    max_new_tokens=20, temperature=0.9, top_k=8, top_p=0.9, seed=1234,
    presence_penalty=0.4, frequency_penalty=0.25,
)


def _run(engine, prompt, sampling_kwargs, **kw):
    async def main():
        return await engine.generate(
            list(prompt), SamplingParams(**sampling_kwargs), **kw
        )

    return asyncio.run(main())


@pytest.mark.parametrize(
    "sampling",
    [
        # seeded (penalties + truncation + per-request seed) subsumes
        # greedy's resurrection machinery; the greedy leg rides the
        # slow tier (~7s — tier-1 wall-clock headroom, ISSUE 14)
        pytest.param(GREEDY, id="greedy", marks=pytest.mark.slow),
        pytest.param(SEEDED, id="seeded"),
    ],
)
def test_crash_mid_decode_resumes_bitwise_dense(tiny, sampling,
                                                flight_recorder):
    config, params = tiny
    factory = _factory(config, params)
    oracle = factory()
    oracle.start()
    expected = _run(oracle, [1, 2, 3, 4, 5], sampling)
    oracle.stop()
    assert len(expected.tokens) == sampling["max_new_tokens"]

    faults.configure("engine_thread_crash@step=2")
    supervisor = EngineSupervisor(factory)
    first_engine = supervisor.engine
    streamed = []
    result = _run(
        supervisor.engine, [1, 2, 3, 4, 5], sampling,
        on_token=lambda token, last: streamed.append(token),
    )
    assert supervisor.restarts == 1
    assert supervisor.state == "serving"
    assert supervisor.engine is not first_engine
    # THE acceptance assertion: the resumed session's full output is
    # bitwise identical to the uncrashed oracle's
    assert result.tokens == expected.tokens
    assert result.finish_reason == expected.finish_reason
    assert result.prompt_tokens == 5
    # the stream saw every token exactly once: the pre-crash prefix from
    # the dead engine, the continuation from the rebuilt one — replay
    # tokens are never re-emitted
    asyncio.run(asyncio.sleep(0))  # drain any queued callbacks
    assert streamed == expected.tokens
    # goodput: the replay prefill is billed as crash_replay recompute
    stats = supervisor.engine.stats
    assert stats["tokens_wasted"].get("crash_replay", 0) > 0
    supervisor.stop()


def test_crash_spares_no_session_and_seeds_survive_together(tiny):
    """Two concurrent sessions, one crash: BOTH resume bitwise — no
    in-flight session is failed (the zero-500s criterion)."""
    config, params = tiny
    factory = _factory(config, params)
    oracle = factory()
    oracle.start()

    async def pair(engine):
        return await asyncio.gather(
            engine.generate([1, 2, 3, 4, 5], SamplingParams(**GREEDY)),
            engine.generate([9, 8, 7], SamplingParams(**SEEDED)),
        )

    expected = asyncio.run(pair(oracle))
    oracle.stop()
    faults.configure("engine_thread_crash@step=2")
    supervisor = EngineSupervisor(factory)
    results = asyncio.run(pair(supervisor.engine))
    assert supervisor.restarts == 1
    for got, want in zip(results, expected):
        assert got.tokens == want.tokens
        assert got.finish_reason == want.finish_reason
    supervisor.stop()


def test_crash_resumes_bitwise_paged_across_block_boundary(tiny,
                                                           flight_recorder):
    """Paged layout: crash lands the replay mid-block (prompt + accepted
    tokens not block-aligned), the rebuilt pool re-teaches it through a
    normal cold prefill, and the continuation matches the oracle
    bitwise. Afterwards a prompt sharing a ≥256-token prefix with the
    resurrected session hits the NEW engine's prefix cache — the
    resurrected state is first-class cache content, not a special case."""
    config, params = tiny
    prompt = [(i * 7) % 250 + 1 for i in range(300)]
    factory = _factory(
        config, params, max_seq_len=512,
        prefill_buckets=[16, 32, 64, 128, 256],
        kv_layout="paged", kv_block_size=16,
    )
    oracle = factory()
    oracle.start()
    expected_g = _run(oracle, prompt, GREEDY)
    expected_s = _run(oracle, prompt, SEEDED)
    oracle.stop()

    for sampling, expected in ((GREEDY, expected_g), (SEEDED, expected_s)):
        faults.reset()
        # crash after chunk 2: 4+4 decode tokens + the prefill token =
        # 9 accepted → replay prefill length 300 + 9 - 1 = 308, which is
        # mid-block at block_size 16 (308 % 16 == 4)
        faults.configure("engine_thread_crash@step=2")
        supervisor = EngineSupervisor(factory)
        result = _run(supervisor.engine, prompt, sampling)
        assert supervisor.restarts == 1
        assert result.tokens == expected.tokens
        engine = supervisor.engine
        assert engine.stats["tokens_wasted"].get("crash_replay", 0) > 0
        if sampling is GREEDY:
            # ≥256-token prefix hit against the resurrected session's
            # published chain on the REBUILT engine
            before = engine.kv_manager.stats["hit_tokens"]
            follow = _run(engine, prompt + [33, 34], GREEDY)
            assert len(follow.tokens) == GREEDY["max_new_tokens"]
            assert engine.kv_manager.stats["hit_tokens"] - before >= 256
        supervisor.stop()


def test_crash_resumes_bitwise_with_spec_decode(tiny):
    """Speculative decoding on: accepted draft tokens are part of the
    replay state; the resumed spec engine continues bitwise."""
    config, params = tiny
    prompt = [5, 6, 7, 8] * 6  # repetition for the prompt-lookup drafter
    factory = _factory(
        config, params, spec_decode="ngram", spec_k=3, spec_ngram=2,
        decode_chunk=2,
    )
    oracle = factory()
    oracle.start()
    expected = _run(oracle, prompt, GREEDY)
    oracle.stop()
    faults.configure("engine_thread_crash@step=2")
    supervisor = EngineSupervisor(factory)
    result = _run(supervisor.engine, prompt, GREEDY)
    assert supervisor.restarts == 1
    assert result.tokens == expected.tokens
    supervisor.stop()


def test_recovery_evidence_metrics_flight_trace(tiny, flight_recorder):
    """Every observability plane carries the recovery: snapshot gauges,
    the recovery_seconds histogram, flight events, the trace span."""
    flight, path = flight_recorder
    config, params = tiny
    factory = _factory(config, params)
    faults.configure("engine_thread_crash@step=1")
    supervisor = EngineSupervisor(factory)

    class SpanRecorder:
        enabled = True
        events = []

        def event(self, name, duration_s, **kw):
            self.events.append((name, duration_s, kw))

    supervisor.tracer = SpanRecorder()
    result = _run(supervisor.engine, [1, 2, 3], GREEDY)
    assert len(result.tokens) == GREEDY["max_new_tokens"]
    assert supervisor.restarts == 1
    gauges = engines_snapshot()
    assert gauges["engine_restarts_total"] >= 1.0
    assert gauges["sessions_resurrected_total"] >= 1.0
    assert gauges["engine_degraded"] == 0.0
    assert 'jax_engine_tokens_wasted_total{reason="crash_replay"}' in gauges
    histograms = engines_histograms()
    assert histograms["engine_recovery_seconds"]["count"] >= 1
    spans = [e for e in SpanRecorder.events if e[0] == "engine.recovery"]
    assert spans and spans[0][2]["sessions"] == 1
    flight.flush()
    kinds = [e["kind"] for e in flight.read_artifact(path)]
    for kind in ("fault_injected", "engine_crash", "engine_recovery",
                 "session_resume"):
        assert kind in kinds, kinds
    phases = [
        e.get("phase") for e in flight.read_artifact(path)
        if e["kind"] == "engine_recovery"
    ]
    assert "begin" in phases and "complete" in phases
    supervisor.stop()


def test_degraded_mode_is_typed_503_not_500(tiny):
    """While rebuilding, submits raise the typed retryable error (the
    HTTP surfaces turn it into 503 + Retry-After), and a supervisor past
    its restart budget fails terminally instead of retrying forever."""
    config, params = tiny
    factory = _factory(config, params)
    supervisor = EngineSupervisor(factory)
    engine = supervisor.engine
    # freeze a rebuild window: a condemned engine with on_crash set
    supervisor.state = "rebuilding"
    engine._crashed = RuntimeError("boom")
    with pytest.raises(api_errors.EngineRebuildingError) as info:
        engine.submit(
            __import__(
                "langstream_tpu.providers.jax_local.engine",
                fromlist=["GenerationRequest"],
            ).GenerationRequest(prompt_tokens=[1], sampling=SamplingParams())
        )
    assert info.value.retry_after_s > 0
    assert engines_snapshot()["engine_degraded"] == 1.0
    engine._crashed = None
    supervisor.state = "serving"
    supervisor.stop()


def test_restart_budget_gives_up(tiny):
    config, params = tiny
    factory = _factory(config, params)
    # fire on EVERY chunk from step 1: the rebuilt engine crashes again
    # immediately → second restart exceeds max_restarts=1 → terminal
    faults.configure("engine_thread_crash@step=1:1.0")
    supervisor = EngineSupervisor(factory, max_restarts=1)
    with pytest.raises(RuntimeError, match="giving up"):
        _run(supervisor.engine, [1, 2, 3], GREEDY)
    assert supervisor.state == "failed"


# ---------------------------------------------------------------------- #
# admission deadlines / load shedding
# ---------------------------------------------------------------------- #
def test_queue_deadline_sheds_with_retry_after(tiny, flight_recorder):
    flight, path = flight_recorder
    config, params = tiny
    engine = DecodeEngine(
        config, params, max_slots=1, max_seq_len=128,
        prefill_buckets=[16], decode_chunk=2, queue_timeout_s=0.02,
    )
    engine.start()

    async def main():
        hog = asyncio.ensure_future(engine.generate(
            [1, 2, 3], SamplingParams(max_new_tokens=64)
        ))
        await asyncio.sleep(0.05)  # hog owns the only slot
        starved = asyncio.ensure_future(engine.generate(
            [4, 5, 6], SamplingParams(max_new_tokens=4)
        ))
        with pytest.raises(api_errors.QueueTimeoutError) as info:
            await starved
        assert info.value.retry_after_s >= 1.0
        await hog
        return info.value

    asyncio.run(main())
    assert engine.stats["requests_shed"] == {"queue_timeout": 1}
    gauges = engines_snapshot()
    assert gauges['requests_shed_total{reason="queue_timeout"}'] >= 1.0
    flight.flush()
    sheds = [
        e for e in flight.read_artifact(path) if e["kind"] == "request_shed"
    ]
    assert sheds and sheds[0]["reason"] == "queue_timeout"
    engine.stop()


# ---------------------------------------------------------------------- #
# watchdog escalation
# ---------------------------------------------------------------------- #
def test_watchdog_escalates_after_n_trips_within_window():
    from types import SimpleNamespace

    from langstream_tpu.runtime.watchdog import EngineWatchdog

    engine = SimpleNamespace(
        stats={
            "decode_chunks": 0, "decode_steps": 0, "decode_token_steps": 0.0,
            "decode_time": 0.0, "prefill_calls": 0, "warm_prefill_calls": 0,
        },
        slots=[SimpleNamespace(active=True)],
        _pending=[],
        kv_manager=None,
    )
    watchdog = EngineWatchdog(
        engine, no_progress_s=10.0, trip_cooldown_s=5.0,
        capture_profile=False, escalate_trips=3, escalate_window_s=100.0,
    )
    escalations = []
    watchdog.on_escalate = escalations.append
    now = 1000.0
    watchdog.check(now=now)  # anchors the stall
    # three no-progress trips, spaced past the cooldown
    for i in range(3):
        now += 15.0
        assert watchdog.check(now=now) == "no_progress"
    assert escalations == ["watchdog_escalation:no_progress"]
    # a fourth trip inside the same window does NOT re-escalate (the
    # restart is already underway)
    now += 15.0
    watchdog.check(now=now)
    assert len(escalations) == 1
    # existing behavior preserved: trips counted, cooldown respected
    assert watchdog.trips == 4


def test_escalation_restart_resurrects_live_session(tiny):
    """The supervisor's second detection arm: a restart REQUEST (the
    watchdog escalation path) on a live engine tears it down cleanly
    and resumes the in-flight session bitwise."""
    config, params = tiny
    factory = _factory(config, params)
    oracle = factory()
    oracle.start()
    expected = _run(oracle, [2, 4, 6, 8], GREEDY)
    oracle.stop()
    supervisor = EngineSupervisor(factory)
    first_engine = supervisor.engine

    async def main():
        task = asyncio.ensure_future(supervisor.engine.generate(
            [2, 4, 6, 8], SamplingParams(**GREEDY)
        ))
        while not first_engine.stats["tokens_generated"]:
            await asyncio.sleep(0.005)
        await asyncio.to_thread(
            supervisor.request_restart, "watchdog_escalation:test"
        )
        return await task

    result = asyncio.run(main())
    assert supervisor.restarts == 1
    assert supervisor.engine is not first_engine
    assert result.tokens == expected.tokens
    supervisor.stop()


# ---------------------------------------------------------------------- #
# OpenAI surface: 503/Retry-After + sibling-cancellation regression
# ---------------------------------------------------------------------- #
async def _post(port, path, payload):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.post(
            f"http://127.0.0.1:{port}{path}", json=payload
        ) as response:
            try:
                body = await response.json(content_type=None)
            except ValueError:
                body = {"raw": await response.text()}
            return response.status, dict(response.headers), body


def test_api_answers_503_with_retry_after_while_rebuilding():
    from langstream_tpu.serving.openai_api import OpenAIApiServer

    class Rebuilding:
        def available(self):
            return 3.0

        async def get_chat_completions(self, *a, **k):  # pragma: no cover
            raise AssertionError("must be gated before the service")

    async def main():
        server = OpenAIApiServer(
            Rebuilding(), model="tiny", host="127.0.0.1", port=0,
        )
        await server.start()
        try:
            port = server.addresses[0][1]
            status, headers, body = await _post(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "stream": False},
            )
            assert status == 503
            assert headers["Retry-After"] == "3"
            assert "rebuilding" in body["error"]["message"]
            # streaming requests are gated BEFORE the SSE response opens
            status, headers, _ = await _post(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "stream": True},
            )
            assert status == 503 and "Retry-After" in headers
        finally:
            await server.stop()

    asyncio.run(main())


def test_sibling_cancel_race_propagates_first_real_error():
    """Regression (ISSUE 9 bugfix): with n>1, when the first exception
    gather surfaces is a CancelledError (a sibling's cancel racing its
    own completion), the ORIGINAL typed error from another sibling must
    reach the client — here as a 503 + Retry-After from a fault-injected
    dispatch error, not a swallowed cancellation."""
    from langstream_tpu.serving.openai_api import OpenAIApiServer

    faults.configure("dispatch_error@step=1")

    class Racy:
        calls = 0

        async def get_chat_completions(self, messages, options, consumer=None):
            Racy.calls += 1
            call = Racy.calls
            if call == 1:
                # completes "cancelled" first — the exception gather
                # surfaces, exactly the race the bugfix targets
                await asyncio.sleep(0.01)
                raise asyncio.CancelledError()
            await asyncio.sleep(0.05)
            try:
                faults.check("dispatch_error")  # first arrival → fires
            except faults.InjectedFault as fault:
                raise api_errors.QueueTimeoutError(
                    f"dispatch failed: {fault}", retry_after_s=2.0
                ) from fault
            raise AssertionError("fault should have fired")

    async def main():
        server = OpenAIApiServer(
            Racy(), model="tiny", host="127.0.0.1", port=0,
        )
        await server.start()
        try:
            port = server.addresses[0][1]
            status, headers, body = await _post(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}], "n": 2},
            )
            assert status == 503, body
            assert "Retry-After" in headers
            assert "dispatch failed" in body["error"]["message"]
        finally:
            await server.stop()

    asyncio.run(main())


def test_provider_surfaces_rebuild_as_typed_unavailable(tiny):
    """JaxCompletionsService.available() + the pre-generate gate: a
    rebuilding supervisor turns new work into the typed retryable error
    end to end (provider level — the HTTP mapping is covered above)."""
    from langstream_tpu.providers.jax_local.provider import (
        JaxCompletionsService,
    )

    service = JaxCompletionsService({
        "model": {"preset": "tiny", "max_seq_len": 128},
        "engine": {"max-slots": 2, "max-seq-len": 128,
                   "queue-timeout-s": 30},
    })
    try:
        assert service._supervisor is not None  # on by default
        assert service.available() is None
        service._supervisor.state = "rebuilding"
        assert service.available() == pytest.approx(
            service._supervisor.retry_after()
        )
        with pytest.raises(api_errors.EngineRebuildingError):
            asyncio.run(service.get_text_completions(
                ["hi"], {"max-tokens": 4}
            ))
        service._supervisor.state = "serving"
        assert service.engine.queue_timeout_s == 30.0
    finally:
        asyncio.run(service.close())


def test_ci_shard_learns_recovery():
    import tools.ci_shard as ci_shard

    assert ci_shard.assign("test_recovery.py") == "kernels-engine"
