"""Pravega topic runtime (topics/pravega.py): RecordWrapper wire-shape
codec, SPI mapping against the in-memory fake client, the registry
entry, and the lib-gated error. Reference:
PravegaTopicConnectionsRuntimeProvider.java (see module docstring)."""

import asyncio
import json

import pytest

from langstream_tpu.api.records import Record
from langstream_tpu.api.topics import OffsetPosition, TopicSpec
from langstream_tpu.topics import create_topic_runtime
from langstream_tpu.topics.pravega import (
    PravegaTopicConnectionsRuntime,
    decode_event,
    encode_event,
    serialise_key,
)

from tests.pravega_mock import FakePravegaModule


def test_envelope_matches_recordwrapper_shape():
    record = Record(
        key="k1", value={"answer": 42}, timestamp=1234,
        headers=(("trace", "abc"), ("n", 7)),
    )
    wire = json.loads(encode_event(record))
    # exactly the reference RecordWrapper fields — its Jackson record
    # deserializer rejects unknown properties
    assert sorted(wire) == ["headers", "key", "timestamp", "value"]
    assert wire["key"] == "k1"
    assert wire["value"] == {"answer": 42}
    assert wire["headers"] == {"trace": "abc", "n": 7}
    assert wire["timestamp"] == 1234

    back = decode_event(encode_event(record), "t")
    assert back.key == "k1"
    assert back.value == {"answer": 42}
    assert dict(back.headers) == {"trace": "abc", "n": 7}
    assert back.origin == "t"
    assert back.timestamp == 1234


def test_serialise_key_rules():
    assert serialise_key(None) is None
    assert serialise_key("route") == "route"
    assert serialise_key(17) == "17"
    assert serialise_key(True) == "true"  # Java String.valueOf(true)
    assert serialise_key({"a": 1}) == '{"a":1}'  # Jackson-compact


def test_produce_consume_through_fake_client():
    fake = FakePravegaModule()
    runtime = PravegaTopicConnectionsRuntime(
        {"client": {"controller-uri": "tcp://ctrl:9090", "scope": "s"}},
        client_module=fake,
    )

    async def main():
        admin = runtime.create_admin()
        await admin.create_topic(TopicSpec(name="events", partitions=2))
        # idempotent second create (reference swallows "exists")
        await admin.create_topic(TopicSpec(name="events", partitions=2))

        producer = runtime.create_producer("agent-1", {"topic": "events"})
        await producer.start()
        await producer.write(Record(key="k", value="hello"))
        await producer.write(Record(value={"x": 1}))
        assert producer.total_in() == 2

        consumer = runtime.create_consumer(
            "agent-2", {"topic": "events", "group": "g1"}
        )
        await consumer.start()
        records = await consumer.read(max_records=10)
        assert [r.value for r in records] == ["hello", {"x": 1}]
        assert records[0].key == "k" and records[0].origin == "events"
        await consumer.commit(records)  # broker-side no-op
        assert await consumer.read(max_records=10) == []
        assert consumer.total_out() == 2

        # same group resumes at the group position; a reader (fresh
        # ephemeral group) sees the stream from the head
        await producer.write(Record(value="late"))
        assert [r.value for r in await consumer.read()] == ["late"]
        reader = runtime.create_reader(
            {"topic": "events"}, OffsetPosition.EARLIEST
        )
        await reader.start()
        assert [r.value for r in await reader.read()] == [
            "hello", {"x": 1}, "late",
        ]
        await reader.close()
        await consumer.close()
        await producer.close()

        # routing key reached the fake writer
        manager = fake.StreamManager("tcp://ctrl:9090")
        assert manager.streams[("s", "events")][0][0] == "k"
        assert manager.segments[("s", "events")] == 2

        await admin.delete_topic("events")
        assert ("s", "events") in manager.sealed
        assert ("s", "events") not in manager.streams
        await admin.delete_topic("events")  # idempotent
        await admin.close()
        await runtime.close()

    asyncio.run(main())


def test_dead_letter_producer_targets_suffixed_stream():
    fake = FakePravegaModule()
    runtime = PravegaTopicConnectionsRuntime({}, client_module=fake)
    dlq = runtime.create_deadletter_producer("a", {"topic": "events"})
    assert dlq.topic == "events-deadletter"


def test_registry_and_import_gate():
    runtime = create_topic_runtime({
        "type": "pravega",
        "configuration": {"client": {"scope": "x"}},
    })
    assert isinstance(runtime, PravegaTopicConnectionsRuntime)
    assert runtime.scope == "x"
    assert runtime.controller_uri == "tcp://localhost:9090"
    # without the client library, first broker contact explains itself
    with pytest.raises(RuntimeError, match="pip install pravega"):
        runtime.manager()


def test_create_topic_surfaces_real_failures():
    """Only the already-exists outcome is tolerated; a dead controller
    must fail deploy, not log 'exists' and continue."""

    class BrokenManager:
        def create_scope(self, scope):
            raise ConnectionError("connection refused: tcp://ctrl:9090")

    class BrokenModule:
        def StreamManager(self, uri):
            return BrokenManager()

    runtime = PravegaTopicConnectionsRuntime({}, client_module=BrokenModule())
    admin = runtime.create_admin()
    with pytest.raises(ConnectionError, match="refused"):
        asyncio.run(admin.create_topic(TopicSpec(name="t")))


def test_read_timeout_does_not_drop_blocked_drain():
    """A get_segment_slice that blocks past the poll timeout makes
    read() return [] — and the drained events arrive on a LATER read
    instead of being lost."""
    import threading
    import time as _time

    fake = FakePravegaModule()
    runtime = PravegaTopicConnectionsRuntime({}, client_module=fake)
    gate = threading.Event()

    async def main():
        admin = runtime.create_admin()
        await admin.create_topic(TopicSpec(name="slow"))
        producer = runtime.create_producer("a", {"topic": "slow"})
        await producer.write(Record(value="v1"))
        consumer = runtime.create_consumer("b", {"topic": "slow", "group": "g"})
        await consumer.start()
        real_drain = consumer._inner._reader.get_segment_slice

        def blocking_slice():
            gate.wait(timeout=10)
            return real_drain()

        consumer._inner._reader.get_segment_slice = blocking_slice
        assert await consumer.read(timeout=0.05) == []  # blocked -> empty
        gate.set()
        deadline = _time.monotonic() + 5
        out = []
        while not out and _time.monotonic() < deadline:
            out = await consumer.read(timeout=0.2)
        assert [r.value for r in out] == ["v1"]
        await consumer.close()

    asyncio.run(main())
