"""Pin the third-party stubs — and the example ports' usage — to the
RECORDED real APIs (VERDICT r4 weak #5).

tests/thirdparty_stubs/{langchain_core,langchain_openai,llama_index,
cassandra} encode the builder's belief about those libraries; nothing
previously tied that belief to the real packages, so the ports could be
green and wrong. MANIFEST.json records the real public signatures at
the pinned versions (regenerable/checkable against the live packages by
tools/gen_thirdparty_manifest.py wherever they are installed). Here:

1. every stub symbol exists and its signature accepts every call shape
   the real signature accepts for the shapes the ports use;
2. every call the ports make (extracted from the port SOURCE by AST for
   constructors/classmethods, plus the curated instance-method list)
   binds against the REAL recorded signature — a port drifting onto a
   stub-only calling convention fails here even though the stub would
   happily accept it.
"""

from __future__ import annotations

import ast
import inspect
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
STUBS = REPO / "tests" / "thirdparty_stubs"
MANIFEST = json.loads((STUBS / "MANIFEST.json").read_text())

PORT_FILES = [
    REPO / "examples/applications/langchain-chat/python/langchain_chat.py",
    REPO / "examples/applications/llamaindex-cassandra-sink/python/"
           "llamaindex_cassandra.py",
]

_KIND = {
    "pos": inspect.Parameter.POSITIONAL_OR_KEYWORD,
    "kwonly": inspect.Parameter.KEYWORD_ONLY,
    "var_pos": inspect.Parameter.VAR_POSITIONAL,
    "var_kw": inspect.Parameter.VAR_KEYWORD,
}


def _signature(params) -> inspect.Signature:
    out = []
    for param in params:
        kind = _KIND[param["kind"]]
        default = (
            inspect.Parameter.empty
            if param["required"] or kind in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD,
            )
            else None
        )
        out.append(inspect.Parameter(param["name"], kind, default=default))
    return inspect.Signature(out)


def _stub_import(module: str):
    sys.path.insert(0, str(STUBS))
    try:
        __import__(module)
        return sys.modules[module]
    finally:
        sys.path.pop(0)


def _resolve(target: str):
    """'pkg.mod.Class.method' | 'pkg.mod.Class' -> (obj, real_params)."""
    parts = target.split(".")
    for split in range(len(parts), 0, -1):
        symbol = ".".join(parts[:split])
        if symbol in MANIFEST["symbols"]:
            entry = MANIFEST["symbols"][symbol]
            module, cls_name = symbol.rsplit(".", 1)
            stub_cls = getattr(_stub_import(module), cls_name)
            rest = parts[split:]
            if not rest:  # constructor
                return stub_cls, entry.get("init", [{
                    "name": "args", "kind": "var_pos", "required": False,
                }, {"name": "kwargs", "kind": "var_kw", "required": False}])
            method = entry["methods"][rest[0]]
            stub_attr = getattr(stub_cls, rest[0])
            # manifest params already omit self/cls for all method kinds
            return stub_attr, method["params"]
    raise KeyError(f"{target} not in manifest")


def _bind(params, n_args: int, kwargs: list):
    signature = _signature(params)
    signature.bind(*([object()] * n_args), **{k: object() for k in kwargs})


def _stub_bind(stub, n_args: int, kwargs: list, *, is_method=False):
    """Bind the call shape against the STUB's actual signature."""
    if inspect.isclass(stub):
        signature = inspect.signature(stub)  # __init__ minus self
    else:
        signature = inspect.signature(stub)
        if is_method:
            # unbound function from the class: skip self
            params = list(signature.parameters.values())
            if params and params[0].name in ("self",):
                signature = signature.replace(parameters=params[1:])
    signature.bind(*([object()] * n_args), **{k: object() for k in kwargs})


# ------------------------------------------------------------------ #
# 1. stub surface: every manifest symbol exists in the stubs with the
#    recorded attributes
# ------------------------------------------------------------------ #
@pytest.mark.parametrize("symbol", sorted(MANIFEST["symbols"]))
def test_stub_symbol_exists_and_matches(symbol):
    entry = MANIFEST["symbols"][symbol]
    module, name = symbol.rsplit(".", 1)
    stub_mod = _stub_import(module)
    assert hasattr(stub_mod, name), f"stub missing {symbol}"
    stub = getattr(stub_mod, name)
    if entry["kind"] == "class":
        assert inspect.isclass(stub), f"{symbol} is not a class in the stub"
    for method, spec in (entry.get("methods") or {}).items():
        assert hasattr(stub, method), f"stub {symbol} missing .{method}"
        if spec.get("classmethod"):
            raw = inspect.getattr_static(stub, method)
            assert isinstance(raw, (classmethod, staticmethod)), (
                f"{symbol}.{method} must be a class/static method"
            )
    # attribute contract: instantiable symbols expose the recorded
    # attributes after construction with minimal string args
    attributes = entry.get("attributes") or []
    if attributes and entry.get("init"):
        required = [
            p for p in entry["init"]
            if p["required"] and p["kind"] in ("pos",)
        ]
        known = entry.get("init_known_kwargs") or []
        try:
            if required:
                instance = stub(*["x"] * len(required))
            elif "text" in known:
                instance = stub(text="x")
            else:
                instance = stub()
        except Exception as error:  # noqa: BLE001
            raise AssertionError(
                f"stub {symbol} not constructible with recorded shape: "
                f"{error!r}"
            ) from None
        for attribute in attributes:
            assert hasattr(instance, attribute), (
                f"stub {symbol} instance lacks .{attribute}"
            )


# ------------------------------------------------------------------ #
# 2. curated instance-method call shapes bind against real AND stub
# ------------------------------------------------------------------ #
@pytest.mark.parametrize(
    "call", MANIFEST["port_calls"], ids=lambda c: c["target"]
)
def test_port_call_shape_binds(call):
    stub, params = _resolve(call["target"])
    # against the recorded REAL signature
    _bind(params, call["args"], call["kwargs"])
    # against the stub as shipped
    is_method = (
        "." in call["target"]
        and call["target"].rsplit(".", 1)[0] in MANIFEST["symbols"]
        and not inspect.isclass(stub)
        and not inspect.ismethod(stub)  # classmethods arrive bound
    )
    _stub_bind(stub, call["args"], call["kwargs"], is_method=is_method)


# ------------------------------------------------------------------ #
# 3. AST sweep of the port sources: every direct constructor /
#    classmethod call on an imported third-party symbol must bind
#    against the recorded real signature (catches a port drifting onto
#    a stub-only lax signature — the from_texts(texts)-without-
#    embedding class of bug)
# ------------------------------------------------------------------ #
def _port_calls_from_source(path: Path):
    tree = ast.parse(path.read_text())
    imported = {}  # local name -> fq symbol
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.split(".")[0] in (
                "langchain_core", "langchain_openai", "llama_index",
                "cassandra",
            )
        ):
            for alias in node.names:
                imported[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    calls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        target = None
        if isinstance(func, ast.Name) and func.id in imported:
            target = imported[func.id]
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in imported
        ):
            target = f"{imported[func.value.id]}.{func.attr}"
        if target is None:
            continue
        n_args = len([a for a in node.args if not isinstance(a, ast.Starred)])
        kwargs = [k.arg for k in node.keywords if k.arg is not None]
        calls.append((target, n_args, kwargs))
    return calls


@pytest.mark.parametrize("path", PORT_FILES, ids=lambda p: p.parent.parent.name)
def test_port_source_calls_bind_against_real_api(path):
    calls = _port_calls_from_source(path)
    assert calls, f"no third-party calls found in {path} (AST sweep broken?)"
    failures = []
    for target, n_args, kwargs in calls:
        try:
            _stub_resolved, params = _resolve(target)
        except KeyError:
            failures.append(f"{target}: symbol not recorded in MANIFEST.json")
            continue
        try:
            _bind(params, n_args, kwargs)
        except TypeError as error:
            failures.append(
                f"{target}({n_args} args, kwargs={kwargs}): does not bind "
                f"against the recorded real signature: {error}"
            )
    assert not failures, "\n".join(failures)
