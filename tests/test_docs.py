"""Config doc model + validation tests."""

import json
import subprocess
import sys

import pytest

from langstream_tpu.model.docs import (
    all_docs,
    generate_docs_model,
    get_doc,
    validate_agent_config,
)


def test_docs_cover_all_registered_and_genai_types():
    from langstream_tpu.compiler.planner import GENAI_STEP_TYPES
    from langstream_tpu.runtime.registry import (
        _ensure_builtin_loaded,
        agent_types,
    )

    _ensure_builtin_loaded()
    documented = set(all_docs())
    missing = (set(agent_types()) | GENAI_STEP_TYPES) - documented
    assert not missing, f"undocumented agent types: {sorted(missing)}"


def test_validate_ok_and_unknown_property():
    assert validate_agent_config("drop-fields", {"fields": ["a"]}) == []
    errors = validate_agent_config("drop-fields", {"fields": ["a"], "oops": 1})
    assert errors and "unknown property 'oops'" in errors[0]


def test_validate_missing_required_and_bad_type():
    errors = validate_agent_config("compute", {})
    assert any("missing required property 'fields'" in e for e in errors)
    errors = validate_agent_config("text-splitter", {"chunk_size": "big"})
    assert any("expects integer" in e for e in errors)


def test_validate_choices():
    errors = validate_agent_config("cast", {"schema-type": "string", "part": "header"})
    assert any("must be one of" in e for e in errors)


def test_unknown_agent_type_passes():
    assert validate_agent_config("my-custom-agent", {"whatever": 1}) == []


def test_allow_unknown_types_accept_extra_keys():
    assert validate_agent_config(
        "python-processor", {"className": "x.Y", "custom-knob": 3}
    ) == []


def test_planner_rejects_bad_config(tmp_path):
    import textwrap

    from langstream_tpu.compiler import build_application, build_execution_plan

    app_dir = tmp_path / "app"
    app_dir.mkdir()
    (app_dir / "pipeline.yaml").write_text(textwrap.dedent("""
        topics:
          - name: "in"
          - name: "out"
        pipeline:
          - name: "bad"
            type: "compute"
            input: "in"
            output: "out"
            configuration:
              fieldz: []
    """))
    (app_dir / "instance.yaml").write_text(textwrap.dedent("""
        instance:
          streamingCluster: {type: memory}
          computeCluster: {type: local}
    """))
    app = build_application(str(app_dir))
    with pytest.raises(ValueError, match="unknown property 'fieldz'"):
        build_execution_plan(app)


def test_docs_match_implementation_keys():
    """Regression: doc entries must accept the keys the implementations
    actually read (strict validation would otherwise reject working
    pipelines)."""
    assert validate_agent_config("re-rank", {"vector-field": "v"}) == []
    assert validate_agent_config("re-rank", {}) == []  # all defaults
    assert validate_agent_config("log-event", {"message": "hi"}) == []
    assert validate_agent_config("file-source", {
        "path": "/tmp", "delete-objects": True,
    }) == []


def test_docs_model_json_serializable():
    model = generate_docs_model()
    assert "ai-chat-completions" in model
    encoded = json.loads(json.dumps(model))
    props = {p["name"] for p in encoded["ai-chat-completions"]["properties"]}
    assert {"messages", "stream-to-topic", "session-field"} <= props


def test_cli_docs_command():
    out = subprocess.run(
        [sys.executable, "-m", "langstream_tpu", "docs", "re-rank"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "MMR" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "langstream_tpu", "docs", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["cast"]["properties"]


def test_blank_placeholder_values():
    """`${globals.x:-}` substitutes to "": optional non-string
    properties treat it as unset (consumer default applies), REQUIRED
    ones fail at plan time, and "" is not a valid boolean literal."""
    from langstream_tpu.model.docs import validate_agent_config

    # blank non-string -> plan-time error with guidance (consumers use
    # config.get(key, default): a PRESENT blank key would bypass the
    # default and crash/flip at runtime)
    errors = validate_agent_config(
        "query-vector-db", {"datasource": "db", "query": "q",
                            "output-field": "o", "only-first": ""}
    )
    assert any("'only-first' is blank" in e and "non-blank default" in e
               for e in errors)
    # blank on a required property errors too
    errors = validate_agent_config("drop-fields", {"fields": ""})
    assert any("'fields' is blank" in e for e in errors)
    # blank STRING properties stay valid ("" is a legitimate string)
    assert validate_agent_config(
        "ai-chat-completions",
        {"model": "m", "messages": [], "completion-field": ""},
    ) == []
    # non-blank wrong type still caught
    errors = validate_agent_config("drop-fields", {"fields": "a,b"})
    assert any("expects list" in e for e in errors)
