"""CI wiring: the shard partition (tools/ci_shard.py) must stay total,
disjoint, and in sync with .github/workflows/ci.yml's matrix — the
analogue of the reference's sharded CI split
(`/root/reference/.github/workflows/ci.yml:28-91`)."""

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import ci_shard  # noqa: E402


def test_partition_total_and_disjoint():
    tests_dir = os.path.join(REPO, "tests")
    names = ci_shard.test_files(tests_dir)
    assert names, "no test files found"
    seen = {}
    for name in names:
        shard = ci_shard.assign(name)  # raises if unassigned
        seen.setdefault(shard, []).append(name)
    # every shard actually runs something (an empty shard silently
    # passes in CI via xargs on no input — catch it here)
    for shard in ci_shard.SHARDS:
        assert seen.get(shard), f"shard {shard} matches no test file"
    assert sum(len(v) for v in seen.values()) == len(names)


def test_workflow_matrix_matches_shard_map():
    workflow = open(
        os.path.join(REPO, ".github", "workflows", "ci.yml")
    ).read()
    block = workflow.split("shard:", 1)[1]
    # every plain "- token" list item after the matrix key; the steps
    # below it are "- uses:/- name:" mappings and don't match. No
    # truncation: an extra matrix entry missing from SHARDS must fail.
    matrix = re.findall(r"^\s*-\s+([a-z0-9-]+)\s*$", block, re.M)
    assert sorted(matrix) == sorted(ci_shard.SHARDS), (
        matrix, list(ci_shard.SHARDS),
    )


def test_cli_lists_files():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ci_shard.py"),
         "kernels-engine"],
        capture_output=True, text=True, check=True,
    ).stdout.splitlines()
    assert any(line.endswith("test_engine.py") for line in out)
    unknown = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ci_shard.py"), "nope"],
        capture_output=True, text=True,
    )
    assert unknown.returncode != 0
