"""The long-running service subcommands (controlplane / operator /
gateway-server) boot as real processes — what the helm chart Deployments
invoke."""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import urllib.request

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


async def _wait_http(url: str, process, timeout=30.0) -> str:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if process.returncode is not None:
            raise AssertionError(
                (await process.stdout.read()).decode(errors="replace")
            )
        try:
            with urllib.request.urlopen(url, timeout=1.0) as response:
                return response.read().decode()
        except Exception:  # noqa: BLE001
            await asyncio.sleep(0.2)
    raise TimeoutError(url)


async def _spawn(args, env_extra, tmp):
    env = {
        "PATH": os.environ.get("PATH", ""),
        "PYTHONPATH": REPO_ROOT,
        "JAX_PLATFORMS": "cpu",
        "HOME": str(tmp),
        **env_extra,
    }
    return await asyncio.create_subprocess_exec(
        "python", "-m", "langstream_tpu", *args,
        env=env, cwd=REPO_ROOT,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )


async def _stop(process):
    if process.returncode is None:
        process.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(process.communicate(), timeout=15)
        except asyncio.TimeoutError:
            process.kill()
            await process.communicate()


@pytest.mark.slow
def test_controlplane_command_boots(tmp_path):
    async def main():
        port = _free_port()
        process = await _spawn(
            ["controlplane", "--host", "127.0.0.1", "--port", str(port),
             "--storage-path", str(tmp_path / "cp"), "--executor", "none"],
            {}, tmp_path,
        )
        try:
            health = await _wait_http(
                f"http://127.0.0.1:{port}/healthz", process
            )
            assert json.loads(health)["status"] == "ok"
            tenants = await _wait_http(
                f"http://127.0.0.1:{port}/api/tenants", process
            )
            assert "default" in json.loads(tenants)
        finally:
            await _stop(process)

    asyncio.run(main())


@pytest.mark.slow
def test_gateway_server_command_boots(tmp_path):
    async def main():
        port = _free_port()
        process = await _spawn(
            ["gateway-server", "--host", "127.0.0.1", "--port", str(port)],
            {"LANGSTREAM_KUBE": "mock"}, tmp_path,
        )
        try:
            health = await _wait_http(
                f"http://127.0.0.1:{port}/healthz", process
            )
            assert json.loads(health)["status"].lower() == "ok"
        finally:
            await _stop(process)

    asyncio.run(main())


def test_gateway_app_watcher_sync():
    """gateway-server discovers apps from Application CRs and registers
    them with topic runtimes; removed CRs unregister and close."""
    import dataclasses as dc

    from langstream_tpu.cli.services import GatewayAppWatcher
    from langstream_tpu.deployer.crds import ApplicationCustomResource
    from langstream_tpu.deployer.kube import MockKubeApi
    from langstream_tpu.gateway import GatewayServer

    async def main():
        kube = MockKubeApi()
        gateway = GatewayServer(port=0)
        watcher = GatewayAppWatcher(gateway, kube)

        definition = {
            "application_id": "w1", "tenant": "t",
            "modules": {}, "gateways": [
                {"id": "g", "type": "produce", "topic": "in"},
            ],
        }
        kube.apply(ApplicationCustomResource(
            name="w1", namespace="t", application=definition,
            instance={"streaming_cluster": {"type": "memory"}},
        ).to_manifest())

        await watcher.sync()
        assert ("t", "w1") in gateway._apps  # noqa: SLF001
        registered = gateway._apps[("t", "w1")]  # noqa: SLF001
        assert registered.application.gateways[0].id == "g"

        # idempotent re-sync
        await watcher.sync()
        assert len(watcher._registered) == 1  # noqa: SLF001

        kube.delete("Application", "t", "w1")
        await watcher.sync()
        assert ("t", "w1") not in gateway._apps  # noqa: SLF001

    asyncio.run(main())
