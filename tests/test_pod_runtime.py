"""Integration: the EXACT command lines from generated manifests boot as
real processes against a served tpulog broker, and records flow.

This is the test VERDICT r2 ordered (missing #1 / weak #3): nothing in
``tests/test_deployer.py`` ever booted from a generated manifest. Here the
deployer Job command writes Agent CRs through the real HTTP kube client
(against the REST facade in ``kube_rest.py``), the operator turns them
into a StatefulSet + Secret, and the Secret's pod-configuration plus the
StatefulSet's container commands are executed as subprocesses. Volume
mount paths (``/app/...``) are remapped into the test tmpdir — the
substitution mirrors what the kubelet's volume mounts do; the command
structure itself is untouched.

Reference flow: ``RuntimeDeployer.java:40`` → ``AgentController`` →
``AgentRunnerStarter.java:39``.
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import io
import json
import os
import signal
import socket
import sys
import textwrap
import urllib.request
import zipfile

import pytest

from langstream_tpu.compiler import build_application
from langstream_tpu.controlplane.codestorage import LocalDiskCodeStorage
from langstream_tpu.deployer.crds import ApplicationCustomResource
from langstream_tpu.deployer.operator import Operator
from langstream_tpu.deployer.resources import (
    generate_deployer_job,
    generate_setup_job,
)
from langstream_tpu.topics.log.server import serve

from tests.kube_rest import MockKubeRestServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PIPELINE = """
    topics:
      - name: "in"
        creation-mode: create-if-not-exists
      - name: "out"
        creation-mode: create-if-not-exists
    pipeline:
      - id: "shout"
        type: "python-processor"
        input: "in"
        output: "out"
        configuration:
          className: "shout_agent.Shout"
"""

AGENT = """
    class Shout:
        def process(self, record):
            return [record.value.upper() + "!"]
"""


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _subst(value: str, tmp: str) -> str:
    """Remap the pod's volume-mount root into the test tmpdir."""
    return value.replace("/app/", f"{tmp}/app/")


async def _run_command(command, env, timeout=90.0):
    process = await asyncio.create_subprocess_exec(
        *command,
        env=env,
        cwd=REPO_ROOT,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )
    out, _ = await asyncio.wait_for(process.communicate(), timeout=timeout)
    assert process.returncode == 0, (
        f"{' '.join(command)} failed rc={process.returncode}:\n"
        f"{out.decode(errors='replace')}"
    )
    return out.decode(errors="replace")


def _http_get(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as response:
        return response.read().decode()


@pytest.mark.slow
def test_generated_manifest_commands_boot_and_flow(tmp_path):
    asyncio.run(_main(tmp_path))


async def _main(tmp_path):
    tmp = str(tmp_path)
    base_env = {
        "PATH": os.environ.get("PATH", ""),
        "PYTHONPATH": REPO_ROOT,
        "JAX_PLATFORMS": "cpu",
        "HOME": os.environ.get("HOME", "/root"),
    }

    # -- a served tpulog broker (the multi-process data plane) ---------- #
    broker = await serve(str(tmp_path / "broker"), host="127.0.0.1", port=0)
    address = broker.address

    # -- the application + its code archive in code storage ------------ #
    app_dir = tmp_path / "src" / "app"
    (app_dir / "python").mkdir(parents=True)
    (app_dir / "pipeline.yaml").write_text(textwrap.dedent(PIPELINE))
    (app_dir / "python" / "shout_agent.py").write_text(textwrap.dedent(AGENT))
    instance_doc = {
        "streaming_cluster": {
            "type": "tpulog",
            "configuration": {"address": address},
        },
        "compute_cluster": {"type": "kubernetes"},
        "globals_": {},
    }
    (tmp_path / "src" / "instance.yaml").write_text(
        json.dumps({"instance": {
            "streamingCluster": instance_doc["streaming_cluster"],
        }})
    )
    application = build_application(
        str(app_dir), instance_file=str(tmp_path / "src" / "instance.yaml")
    )
    application.application_id = "podapp"
    definition = dataclasses.asdict(application)
    definition.pop("secrets", None)
    definition.pop("instance", None)

    archive = io.BytesIO()
    with zipfile.ZipFile(archive, "w") as zf:
        zf.write(app_dir / "python" / "shout_agent.py",
                 "python/shout_agent.py")
    storage_root = str(tmp_path / "codestore")
    storage = LocalDiskCodeStorage(storage_root)
    code_id = storage.store("default", "podapp", archive.getvalue())

    # -- deployer Job: its exact command writes Agent CRs over HTTP ----- #
    kube_server = MockKubeRestServer()
    await kube_server.start()
    try:
        app_cr = ApplicationCustomResource(
            name="podapp",
            namespace="default",
            application=definition,
            instance=instance_doc,
            code_archive_id=code_id,
        )
        # the control plane writes the Application CR; the deployer Job
        # (below) does the planning, so mark the app-level reconcile done —
        # otherwise the operator's orphan sweep removes the agent CRs
        kube_server.kube.apply(app_cr.to_manifest())
        kube_server.kube.patch_status(
            "Application", "default", "podapp",
            {"phase": "DEPLOYED", "observedGeneration": 1},
        )
        deployer_job = generate_deployer_job(app_cr)
        job_container = deployer_job["spec"]["template"]["spec"]["containers"][0]
        job_env = dict(base_env)
        for entry in job_container["env"]:
            job_env[entry["name"]] = entry["value"]
        job_env["LANGSTREAM_KUBE_URL"] = kube_server.url
        await _run_command(job_container["command"], job_env)

        agents = kube_server.kube.list("Agent", "default")
        assert [doc["metadata"]["name"] for doc in agents] == ["podapp-shout"]

        # -- setup Job: its exact command creates the topics ------------ #
        setup_job = generate_setup_job(app_cr)
        setup_container = setup_job["spec"]["template"]["spec"]["containers"][0]
        setup_env = dict(base_env)
        for entry in setup_container["env"]:
            setup_env[entry["name"]] = entry["value"]
        await _run_command(setup_container["command"], setup_env)

        # -- operator: Agent CR -> StatefulSet + Secret ----------------- #
        operator = Operator(
            kube_server.kube,
            code_storage_config={"type": "local-disk", "path": storage_root},
        )
        operator.reconcile()
        sts = kube_server.kube.get("StatefulSet", "default", "podapp-shout")
        secret = kube_server.kube.get("Secret", "default", "podapp-shout")
        assert sts is not None and secret is not None

        # materialize the Secret volume mount
        config_dir = tmp_path / "app" / "config"
        config_dir.mkdir(parents=True)
        payload = base64.b64decode(
            secret["data"]["pod-configuration.json"]
        )
        (config_dir / "pod-configuration.json").write_bytes(payload)
        (tmp_path / "app" / "code").mkdir()
        (tmp_path / "app" / "state").mkdir()

        pod_spec = sts["spec"]["template"]["spec"]

        # -- init container: code-download ------------------------------ #
        init = pod_spec["initContainers"][0]
        init_env = dict(base_env)
        for entry in init["env"]:
            init_env[entry["name"]] = entry["value"]
        init_command = [_subst(part, tmp) for part in init["command"]]
        await _run_command(init_command, init_env)
        assert (tmp_path / "app" / "code" / "python" / "shout_agent.py").exists()

        # -- main container: agent-runner ------------------------------- #
        runner = pod_spec["containers"][0]
        runner_env = dict(base_env)
        for entry in runner["env"]:
            runner_env[entry["name"]] = _subst(entry["value"], tmp)
        http_port = _free_port()
        runner_env["LANGSTREAM_HTTP_PORT"] = str(http_port)
        runner_command = [_subst(part, tmp) for part in runner["command"]]
        process = await asyncio.create_subprocess_exec(
            *runner_command,
            env=runner_env,
            cwd=REPO_ROOT,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )
        try:
            # readiness probe path from the manifest
            ready_url = f"http://127.0.0.1:{http_port}/ready"
            for _ in range(300):
                if process.returncode is not None:
                    break
                try:
                    _http_get(ready_url, timeout=1.0)
                    break
                except Exception:  # noqa: BLE001 — not up yet
                    await asyncio.sleep(0.2)
            else:
                raise TimeoutError("runner never became ready")
            assert process.returncode is None, (
                await process.stdout.read()  # type: ignore[union-attr]
            ).decode(errors="replace")

            # -- records flow through the exec'd pod -------------------- #
            from langstream_tpu.api.records import Record
            from langstream_tpu.api.topics import OffsetPosition
            from langstream_tpu.topics.log.client import (
                RemoteTopicConnectionsRuntime,
            )

            runtime = RemoteTopicConnectionsRuntime(address)
            producer = runtime.create_producer("test", {"topic": "in"})
            await producer.start()
            await producer.write(Record(value="hello"))
            reader = runtime.create_reader(
                {"topic": "out"}, OffsetPosition.EARLIEST
            )
            await reader.start()
            got = []
            deadline = asyncio.get_event_loop().time() + 30
            while not got:
                if asyncio.get_event_loop().time() > deadline:
                    raise TimeoutError("no output record")
                got.extend(await reader.read(timeout=0.3))
            assert got[0].value == "HELLO!"
            await producer.close()
            await reader.close()
            await runtime.close()

            # -- /info + /metrics (reference AgentRunner.java:99-113) --- #
            info = json.loads(
                _http_get(f"http://127.0.0.1:{http_port}/info")
            )
            assert info["application-id"] == "podapp"
            assert info["agents"][0]["stats"]["records-in"] >= 1
            metrics = _http_get(f"http://127.0.0.1:{http_port}/metrics")
            assert "records_in_total" in metrics
            assert "# TYPE" in metrics

            # -- graceful drain on SIGTERM ------------------------------ #
            process.send_signal(signal.SIGTERM)
            out, _ = await asyncio.wait_for(process.communicate(), timeout=30)
            assert process.returncode == 0, out.decode(errors="replace")
        finally:
            if process.returncode is None:
                process.kill()
                await process.communicate()
    finally:
        await kube_server.stop()
        await broker.close()
