"""Avro binary codec + Confluent framing + schema-registry decode path
(reference: langstream-agents-commons Avro converters + registry
serializers)."""

from __future__ import annotations

import asyncio
import json
import struct
import threading

import pytest
from aiohttp import web

from langstream_tpu.topics.kafka import avro

USER_SCHEMA = {
    "type": "record",
    "name": "User",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "age", "type": "long"},
        {"name": "email", "type": ["null", "string"], "default": None},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "scores", "type": {"type": "map", "values": "double"}},
        {"name": "kind", "type": {
            "type": "enum", "name": "Kind", "symbols": ["A", "B"],
        }},
        {"name": "active", "type": "boolean"},
        {"name": "blob", "type": "bytes"},
    ],
}

USER = {
    "name": "ada",
    "age": 36,
    "email": "ada@example.com",
    "tags": ["x", "y"],
    "scores": {"m": 1.5},
    "kind": "B",
    "active": True,
    "blob": b"\x01\x02",
}


def test_roundtrip_all_types():
    payload = avro.encode(avro.parse_schema(USER_SCHEMA), USER)
    assert avro.decode_bytes(USER_SCHEMA, payload) == USER


def test_golden_vector_hand_encoded():
    """Spec-derived byte check: record {s: string, n: long} with
    ("hi", -2) encodes as len-zigzag(2)=0x04, 'h','i', zigzag(-2)=0x03."""
    schema = {
        "type": "record", "name": "T",
        "fields": [
            {"name": "s", "type": "string"},
            {"name": "n", "type": "long"},
        ],
    }
    payload = avro.encode(schema, {"s": "hi", "n": -2})
    assert payload == b"\x04hi\x03"
    assert avro.decode_bytes(schema, payload) == {"s": "hi", "n": -2}


def test_union_null_branch_and_confluent_frame():
    payload = avro.encode(
        avro.parse_schema(USER_SCHEMA), {**USER, "email": None}
    )
    assert avro.decode_bytes(USER_SCHEMA, payload)["email"] is None

    framed = avro.encode_confluent(7, USER_SCHEMA, USER)
    assert framed[0] == 0
    assert struct.unpack(">I", framed[1:5])[0] == 7
    assert avro.is_confluent_framed(framed)
    assert not avro.is_confluent_framed(b"plain text")
    schema_id, body = avro.split_confluent(framed)
    assert schema_id == 7
    assert avro.decode_bytes(USER_SCHEMA, body) == USER


class _Registry:
    def __init__(self, schemas):
        self.schemas = schemas
        self.requests = 0
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )
        self._thread.start()
        self._runner = None
        self.port = None

    def __enter__(self):
        async def go():
            app = web.Application()

            async def get_schema(request):
                self.requests += 1
                schema_id = int(request.match_info["id"])
                if schema_id not in self.schemas:
                    return web.json_response({}, status=404)
                return web.json_response(
                    {"schema": json.dumps(self.schemas[schema_id])}
                )

            app.router.add_get("/schemas/ids/{id}", get_schema)
            self._runner = web.AppRunner(app, access_log=None)
            await self._runner.setup()
            site = web.TCPSite(self._runner, "127.0.0.1", 0)
            await site.start()
            return site._server.sockets[0].getsockname()[1]  # noqa: SLF001

        self.port = asyncio.run_coroutine_threadsafe(
            go(), self._loop
        ).result(10)
        return self

    def __exit__(self, *exc):
        asyncio.run_coroutine_threadsafe(
            self._runner.cleanup(), self._loop
        ).result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)


def test_consumer_decodes_foreign_confluent_records():
    """A record produced by a FOREIGN Confluent-Avro producer (no
    ls-meta envelope) decodes into a dict value; framework records are
    untouched."""
    from langstream_tpu.api.records import Record
    from langstream_tpu.api.topics import OffsetPosition, TopicSpec
    from langstream_tpu.topics.kafka import protocol as proto
    from langstream_tpu.topics.kafka.runtime import (
        KafkaTopicConnectionsRuntime,
    )
    from langstream_tpu.topics.kafka.server import serve_kafka_facade

    schema = {
        "type": "record", "name": "Evt",
        "fields": [{"name": "q", "type": "string"}],
    }

    async def main(registry_port):
        facade = await serve_kafka_facade()
        runtime = KafkaTopicConnectionsRuntime({
            "bootstrapServers": facade.bootstrap,
            "schemaRegistryUrl": f"http://127.0.0.1:{registry_port}",
        })
        try:
            admin = runtime.create_admin()
            await admin.create_topic(TopicSpec(name="t"))
            # foreign producer: raw confluent-framed value, no envelope
            framed = avro.encode_confluent(42, schema, {"q": "hello"})
            batch = proto.encode_record_batch([(None, framed, [], 1000)])
            await runtime._client.produce("t", 0, batch)  # noqa: SLF001
            # framework producer: envelope, must pass through unchanged
            producer = runtime.create_producer("p", {"topic": "t"})
            await producer.write(Record(value={"native": True}))

            consumer = runtime.create_consumer(
                "a", {"topic": "t", "group": "g"}
            )
            await consumer.start()
            got = []
            for _ in range(100):
                got.extend(await consumer.read(timeout=0.2))
                if len(got) >= 2:
                    break
            assert got[0].value == {"q": "hello"}   # avro-decoded
            assert got[1].value == {"native": True}  # envelope path
            await consumer.close()
        finally:
            await runtime.close()
            await facade.close()

    with _Registry({42: schema}) as registry:
        asyncio.run(main(registry.port))
        assert registry.requests == 1  # schema cached after first fetch


def test_pipeline_publishes_confluent_avro_for_declared_schema(tmp_path):
    """A YAML app whose output topic declares an avro schema publishes
    Confluent-framed records a foreign consumer can read (write-side
    interop: registry registration + framing, no ls-meta envelope)."""
    import textwrap

    from langstream_tpu.api.records import Record
    from langstream_tpu.runtime.local import run_application
    from langstream_tpu.topics.kafka.server import serve_kafka_facade

    schema_json = json.dumps({
        "type": "record", "name": "Out",
        "fields": [{"name": "text", "type": "string"}],
    })

    async def main(registry_port):
        facade = await serve_kafka_facade()
        app_dir = tmp_path / "app"
        (app_dir / "python").mkdir(parents=True)
        (app_dir / "pipeline.yaml").write_text(textwrap.dedent(f"""
            topics:
              - name: "in"
                creation-mode: create-if-not-exists
              - name: "out"
                creation-mode: create-if-not-exists
                schema:
                  type: avro
                  schema: '{schema_json}'
            pipeline:
              - id: "wrap"
                type: "python-processor"
                input: "in"
                output: "out"
                configuration:
                  className: "wrap_agent.Wrap"
        """))
        (app_dir / "python" / "wrap_agent.py").write_text(textwrap.dedent("""
            class Wrap:
                def process(self, record):
                    return [{"text": record.value.upper()}]
        """))
        (tmp_path / "instance.yaml").write_text(textwrap.dedent(f"""
            instance:
              streamingCluster:
                type: kafka
                configuration:
                  bootstrapServers: "{facade.bootstrap}"
                  schemaRegistryUrl: "http://127.0.0.1:{registry_port}"
        """))
        runner = await run_application(
            str(app_dir), instance_file=str(tmp_path / "instance.yaml")
        )
        try:
            producer = runner.producer("in")
            await producer.start()
            await producer.write(Record(value="ping"))
            # read the RAW bytes off the broker like a foreign consumer
            from langstream_tpu.topics.kafka import protocol as proto

            raw = []
            for _ in range(150):
                records, _hw = await runner.topic_runtime._client.fetch(  # noqa: SLF001
                    "out", 0, 0, max_wait_ms=200
                )
                raw = records
                if raw:
                    break
            assert raw, "nothing produced"
            framed = raw[0].value
            assert avro.is_confluent_framed(framed)
            schema_id, body = avro.split_confluent(framed)
            decoded = avro.decode_bytes(json.loads(schema_json), body)
            assert decoded == {"text": "PING"}
            assert not any(n == "ls-meta" for n, _ in raw[0].headers)
        finally:
            await runner.stop()
            await facade.close()

    # simple registry mock with register support
    registered = {}

    class _Reg:
        def __init__(self):
            self._loop = asyncio.new_event_loop()
            self._thread = threading.Thread(
                target=self._loop.run_forever, daemon=True
            )
            self._thread.start()
            self._runner = None
            self.port = None

        def __enter__(self):
            async def go():
                app = web.Application()

                async def register(request):
                    body = await request.json()
                    registered[request.match_info["subject"]] = body["schema"]
                    return web.json_response({"id": 99})

                async def get_schema(request):
                    return web.json_response(
                        {"schema": list(registered.values())[0]}
                    )

                app.router.add_post(
                    "/subjects/{subject}/versions", register
                )
                app.router.add_get("/schemas/ids/{id}", get_schema)
                self._runner = web.AppRunner(app, access_log=None)
                await self._runner.setup()
                site = web.TCPSite(self._runner, "127.0.0.1", 0)
                await site.start()
                return site._server.sockets[0].getsockname()[1]  # noqa: SLF001

            self.port = asyncio.run_coroutine_threadsafe(
                go(), self._loop
            ).result(10)
            return self

        def __exit__(self, *exc):
            asyncio.run_coroutine_threadsafe(
                self._runner.cleanup(), self._loop
            ).result(10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    with _Reg() as registry:
        asyncio.run(main(registry.port))
        assert "out-value" in registered  # subject registered


def test_plain_string_schema_publishes_raw_utf8():
    """schema type 'string' publishes envelope-free UTF-8 any foreign
    consumer reads directly."""
    from langstream_tpu.api.records import Record
    from langstream_tpu.api.topics import TopicSpec
    from langstream_tpu.topics.kafka.runtime import (
        KafkaTopicConnectionsRuntime,
    )
    from langstream_tpu.topics.kafka.server import serve_kafka_facade

    async def main():
        facade = await serve_kafka_facade()
        runtime = KafkaTopicConnectionsRuntime(
            {"bootstrapServers": facade.bootstrap}
        )
        try:
            admin = runtime.create_admin()
            await admin.create_topic(TopicSpec(name="t"))
            producer = runtime.create_producer(
                "p", {"topic": "t", "schema": {"type": "string"}}
            )
            await producer.write(Record(value="plain text", key="k1"))
            records, _hw = await runtime._client.fetch(  # noqa: SLF001
                "t", 0, 0, max_wait_ms=500
            )
            assert records[0].value == b"plain text"
            assert records[0].key == b"k1"
            assert not any(n == "ls-meta" for n, _ in records[0].headers)
        finally:
            await runtime.close()
            await facade.close()

    asyncio.run(main())
