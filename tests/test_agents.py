import asyncio
import json

import pytest

from langstream_tpu.api import Record
from langstream_tpu.api.agent import AgentContext
from langstream_tpu.runtime.registry import create_agent
from langstream_tpu.runtime.runner import process_and_collect
from langstream_tpu.topics.memory import MemoryBroker, MemoryTopicConnectionsRuntime


def run(coro):
    return asyncio.run(coro)


async def make(agent_type, config, **ctx_kwargs):
    agent = create_agent(agent_type)
    agent.agent_id = f"test-{agent_type}"
    await agent.init(config)
    await agent.set_context(AgentContext(agent_id="t", **ctx_kwargs))
    await agent.start()
    return agent


async def one(agent, record):
    results = await process_and_collect(agent, [record])
    if results[0].error:
        raise results[0].error
    return results[0].result_records


# ----------------------------- text agents ----------------------------- #
def test_document_to_json():
    async def main():
        agent = await make("document-to-json", {"text-field": "question"})
        out = await one(agent, Record(value=b"hello", headers=(("h", "1"),)))
        assert out[0].value == {"question": "hello", "h": "1"}

    run(main())


def test_text_splitter_chunks_and_headers():
    async def main():
        agent = await make(
            "text-splitter",
            {"chunk_size": 6, "chunk_overlap": 0, "length_function": "length"},
        )
        text = "aaa bbb ccc ddd"
        out = await one(agent, Record(value=text))
        assert len(out) > 1
        assert "".join(r.value.replace(" ", "") for r in out) == text.replace(" ", "")
        assert out[0].header("chunk_id") == "0"
        assert out[0].header("text_num_chunks") == str(len(out))

    run(main())


def test_text_splitter_overlap():
    async def main():
        agent = await make(
            "text-splitter",
            {"chunk_size": 10, "chunk_overlap": 4, "length_function": "length"},
        )
        out = await one(agent, Record(value="one two three four five"))
        chunks = [r.value for r in out]
        assert len(chunks) >= 2
        # overlap: consecutive chunks share some text
        assert any(
            chunks[i].split()[-1] == chunks[i + 1].split()[0]
            for i in range(len(chunks) - 1)
        )

    run(main())


def test_text_normaliser():
    async def main():
        agent = await make("text-normaliser", {})
        out = await one(agent, Record(value="  Hello   WORLD  \n  second  "))
        assert out[0].value == "hello world\nsecond"

    run(main())


def test_language_detector():
    async def main():
        agent = await make("language-detector", {"property": "language"})
        out = await one(
            agent, Record(value="the cat is in the house and it is happy")
        )
        assert out[0].header("language") == "en"
        agent2 = await make(
            "language-detector", {"allowedLanguages": ["fr"]}
        )
        filtered = await one(
            agent2, Record(value="the cat is in the house and it is happy")
        )
        assert filtered == []

    run(main())


def test_text_extractor_html():
    async def main():
        agent = await make("text-extractor", {})
        html_doc = "<html><head><style>x{}</style></head><body><h1>Title</h1><p>Body &amp; soul</p><script>var x;</script></body></html>"
        out = await one(agent, Record(value=html_doc))
        assert "Title" in out[0].value
        assert "Body & soul" in out[0].value
        assert "var x" not in out[0].value

    run(main())


# ----------------------------- flow agents ----------------------------- #
def test_dispatch_routes():
    async def main():
        broker = MemoryBroker()
        rt = MemoryTopicConnectionsRuntime(broker)
        agent = await make(
            "dispatch",
            {
                "routes": [
                    {"when": "properties['lang'] == 'fr'", "destination": "french"},
                    {"when": "properties['lang'] == 'spam'", "action": "drop"},
                ]
            },
            topic_connections=rt,
        )
        passed = await one(agent, Record(value="v", headers=(("lang", "en"),)))
        assert len(passed) == 1
        routed = await one(agent, Record(value="bonjour", headers=(("lang", "fr"),)))
        assert routed == []
        dropped = await one(agent, Record(value="x", headers=(("lang", "spam"),)))
        assert dropped == []

        from langstream_tpu.api import OffsetPosition

        reader = rt.create_reader({"topic": "french"}, OffsetPosition.EARLIEST)
        french = await reader.read()
        assert [r.value for r in french] == ["bonjour"]
        await agent.close()

    run(main())


def test_timer_source():
    async def main():
        agent = await make(
            "timer-source",
            {
                "period-seconds": 0.05,
                "fields": [{"name": "value.tick", "expression": "fn.now()"}],
            },
        )
        got = []
        deadline = asyncio.get_event_loop().time() + 3
        while len(got) < 2 and asyncio.get_event_loop().time() < deadline:
            got.extend(await agent.read())
        assert len(got) >= 2
        assert got[0].value["tick"] > 0

    run(main())


def test_trigger_event():
    async def main():
        broker = MemoryBroker()
        rt = MemoryTopicConnectionsRuntime(broker)
        agent = await make(
            "trigger-event",
            {
                "when": "value.n > 10",
                "destination": "alerts",
                "fields": [{"name": "value.alert", "expression": "value.n"}],
            },
            topic_connections=rt,
        )
        out1 = await one(agent, Record(value={"n": 5}))
        out2 = await one(agent, Record(value={"n": 50}))
        assert len(out1) == 1 and len(out2) == 1  # continue-processing default

        from langstream_tpu.api import OffsetPosition

        reader = rt.create_reader({"topic": "alerts"}, OffsetPosition.EARLIEST)
        alerts = await reader.read()
        assert [a.value for a in alerts] == [{"alert": 50}]
        await agent.close()

    run(main())


# --------------------------- vector agents ----------------------------- #
def test_vector_sink_and_query_roundtrip():
    async def main():
        import langstream_tpu.agents.vectorstore as vs

        vs._SHARED_STORES.clear()
        resources = {
            "vdb": {
                "type": "datasource",
                "configuration": {
                    "service": "vector",
                    "name": "test-store",
                    "dimensions": 3,
                },
            }
        }
        sink = await make(
            "vector-db-sink",
            {
                "datasource": "vdb",
                "vector.id": "value.doc_id",
                "vector.vector": "value.embeddings",
                "vector.text": "value.text",
            },
            resources=resources,
        )
        docs = [
            ("a", [1.0, 0.0, 0.0], "doc about jax"),
            ("b", [0.0, 1.0, 0.0], "doc about xla"),
            ("c", [0.9, 0.1, 0.0], "doc about pallas"),
        ]
        for doc_id, vec, text in docs:
            await sink.write(
                Record(value={"doc_id": doc_id, "embeddings": vec, "text": text})
            )

        query = await make(
            "query-vector-db",
            {
                "datasource": "vdb",
                "query": json.dumps(
                    {"action": "search", "vector": "?", "top-k": 2}
                ),
                "fields": ["value.question_embeddings"],
                "output-field": "value.results",
            },
            resources=resources,
        )
        out = await one(
            query, Record(value={"question_embeddings": [1.0, 0.05, 0.0]})
        )
        results = out[0].value["results"]
        assert [r["id"] for r in results] == ["a", "c"]
        assert results[0]["text"] == "doc about jax"
        assert results[0]["similarity"] > 0.9

    run(main())


def test_rerank_mmr():
    async def main():
        agent = await make(
            "re-rank",
            {
                "field": "value.candidates",
                "output-field": "value.ranked",
                "query-embeddings": "value.qv",
                "vector-field": "vector",
                "max": 2,
                "lambda": 0.3,  # diversity-favoring: MMR must pick 'div' over 'dup2'
            },
        )
        # two near-duplicates close to the query + one diverse
        record = Record(
            value={
                "qv": [1.0, 0.0],
                "candidates": [
                    {"id": "dup1", "vector": [1.0, 0.0]},
                    {"id": "dup2", "vector": [0.99, 0.01]},
                    {"id": "div", "vector": [0.5, 0.5]},
                ],
            }
        )
        out = await one(agent, record)
        ranked = [c["id"] for c in out[0].value["ranked"]]
        # MMR picks the diverse doc second, not the duplicate
        assert ranked == ["dup1", "div"]

    run(main())


# --------------------------- datasources ------------------------------- #
def test_memory_datasource():
    async def main():
        from langstream_tpu.agents.datasource import MemoryDataSource

        source = MemoryDataSource(
            {"tables": {"users": [{"id": 1, "name": "ada"}, {"id": 2, "name": "alan"}]}}
        )
        rows = await source.query(
            json.dumps({"table": "users", "where": {"id": "?"}}).replace('"?"', "?"),
            [2],
        )
        assert rows == [{"id": 2, "name": "alan"}]

    run(main())


def test_gated_datasource_errors():
    async def main():
        from langstream_tpu.agents.datasource import DataSourceRegistry

        # cassandra (CQL) stays gated; milvus moved to the REST-native
        # implementations in external_stores.py
        registry = DataSourceRegistry(
            {"db": {"configuration": {"service": "cassandra"}}}
        )
        with pytest.raises(ValueError, match="client library"):
            registry.resolve("db")

    run(main())


# --------------------------- file source ------------------------------- #
def test_file_source(tmp_path):
    async def main():
        (tmp_path / "a.txt").write_text("alpha")
        (tmp_path / "b.txt").write_text("beta")
        (tmp_path / "c.bin").write_text("skip")
        agent = await make(
            "file-source",
            {"path": str(tmp_path), "file-extensions": "txt",
             "delete-objects": True, "idle-time": 0.01},
        )
        records = await agent.read()
        assert sorted(r.value for r in records) == [b"alpha", b"beta"]
        await agent.commit(records)
        assert sorted(p.name for p in tmp_path.iterdir()) == ["c.bin"]

    run(main())


def test_python_agents_same_module_name_no_collision(tmp_path):
    """Two apps shipping the SAME user module name must not shadow each
    other in one process (per-pythonPath namespacing, like plugins)."""
    import textwrap

    for name, body in (
        ("app_a", "class P:\n    def process(self, record):\n        return [record.value + '-A']"),
        ("app_b", "class P:\n    def process(self, record):\n        return [record.value + '-B']"),
    ):
        d = tmp_path / name / "python"
        d.mkdir(parents=True)
        (d / "dup_module.py").write_text(body)

    async def main():
        outs = []
        for name in ("app_a", "app_b"):
            agent = await make(
                "python-processor",
                {
                    "className": "dup_module.P",
                    "pythonPath": [str(tmp_path / name / "python")],
                },
            )
            out = await one(agent, Record(value="x"))
            outs.append(out[0].value)
        assert outs == ["x-A", "x-B"]

    run(main())
