"""Deployer tests: CRD round-trips, StatefulSet/TPU manifest generation,
operator reconcile on the mock K8s API, and the control-plane→operator
composition (KubernetesExecutor)."""

import asyncio
import io
import zipfile

import pytest

from langstream_tpu.compiler.parser import build_application
from langstream_tpu.deployer import (
    AgentCustomResource,
    ApplicationCustomResource,
    MockKubeApi,
    Operator,
    agent_crd_schema,
    application_crd_schema,
    generate_setup_job,
    generate_statefulset,
)
from langstream_tpu.deployer.operator import KubernetesExecutor
from langstream_tpu.deployer.resources import tpu_topology

PIPELINE = """
topics:
  - name: input-topic
    creation-mode: create-if-not-exists
  - name: output-topic
    creation-mode: create-if-not-exists
pipeline:
  - name: "chat"
    type: ai-chat-completions
    input: input-topic
    output: output-topic
    resources:
      parallelism: 2
      size: 8
      disk:
        size: 10Gi
    configuration:
      completion-field: value.answer
      messages:
        - role: user
          content: "{{ value.question }}"
"""


def make_agent_cr(parallelism=2, size=8, disk=None):
    return AgentCustomResource(
        name="demo-chat", namespace="acme", application_id="demo",
        agent_node={"id": "chat"}, streaming_cluster={"type": "memory"},
        parallelism=parallelism, size=size, disk=disk, checksum="abc",
    )


def test_crd_schemas_are_k8s_shaped():
    for schema in (application_crd_schema(), agent_crd_schema()):
        assert schema["kind"] == "CustomResourceDefinition"
        version = schema["spec"]["versions"][0]
        assert version["schema"]["openAPIV3Schema"]["type"] == "object"


def test_agent_cr_manifest_roundtrip():
    cr = make_agent_cr(disk={"size": "10Gi"})
    doc = cr.to_manifest()
    back = AgentCustomResource.from_manifest(doc)
    assert back == cr


def test_statefulset_tpu_mapping():
    sts = generate_statefulset(make_agent_cr(parallelism=2, size=8))
    spec = sts["spec"]
    assert spec["replicas"] == 2
    pod = spec["template"]["spec"]
    assert pod["nodeSelector"] == tpu_topology(8)
    container = pod["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "8"
    assert container["livenessProbe"]["httpGet"]["path"] == "/info"
    assert pod["initContainers"][0]["name"] == "code-download"


def test_statefulset_multihost_replicas():
    # 16 chips/replica on v5e = 2 hosts per replica → replicas × hosts pods
    sts = generate_statefulset(make_agent_cr(parallelism=2, size=16))
    assert sts["spec"]["replicas"] == 4
    env = {
        e["name"]: e.get("value")
        for e in sts["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    assert env["LANGSTREAM_HOSTS_PER_REPLICA"] == "2"


def test_statefulset_cpu_agent_and_pvc():
    sts = generate_statefulset(
        make_agent_cr(parallelism=1, size=0, disk={"size": "5Gi"})
    )
    pod = sts["spec"]["template"]["spec"]
    assert pod["nodeSelector"] == {}
    assert "google.com/tpu" not in pod["containers"][0]["resources"].get(
        "requests", {}
    )
    claims = sts["spec"]["volumeClaimTemplates"]
    assert claims[0]["spec"]["resources"]["requests"]["storage"] == "5Gi"


def test_invalid_chip_count_rejected():
    with pytest.raises(ValueError):
        generate_statefulset(make_agent_cr(size=3))


def _app_cr(tmp_path, pipeline=PIPELINE):
    app_dir = tmp_path / "app"
    app_dir.mkdir(exist_ok=True)
    (app_dir / "pipeline.yaml").write_text(pipeline)
    application = build_application(str(app_dir))
    import dataclasses

    definition = dataclasses.asdict(application)
    definition.pop("secrets")
    instance = definition.pop("instance")
    return ApplicationCustomResource(
        name="demo", namespace="acme", application=definition,
        instance=instance, checksum="c1", code_archive_id="code-1",
    )


def test_operator_reconciles_app_to_statefulsets(tmp_path):
    kube = MockKubeApi()
    operator = Operator(kube)
    kube.apply(_app_cr(tmp_path).to_manifest())
    operator.reconcile()

    agents = kube.list("Agent", "acme")
    assert len(agents) == 1
    sts = kube.list("StatefulSet", "acme")
    assert len(sts) == 1
    assert sts[0]["spec"]["replicas"] == 2
    app = kube.get("Application", "acme", "demo")
    assert app["status"]["phase"] == "DEPLOYED"
    agent = kube.get("Agent", "acme", agents[0]["metadata"]["name"])
    assert agent["status"]["phase"] == "DEPLOYED"
    # reconcile is idempotent
    operator.reconcile()
    assert len(kube.list("StatefulSet", "acme")) == 1


def test_operator_cleans_up_orphans(tmp_path):
    kube = MockKubeApi()
    operator = Operator(kube)
    kube.apply(_app_cr(tmp_path).to_manifest())
    operator.reconcile()
    assert kube.list("StatefulSet", "acme")
    kube.delete("Application", "acme", "demo")
    operator.reconcile()
    assert not kube.list("Agent", "acme")
    assert not kube.list("StatefulSet", "acme")
    assert not kube.list("Secret", "acme")


def test_operator_handles_spec_update(tmp_path):
    kube = MockKubeApi()
    operator = Operator(kube)
    cr = _app_cr(tmp_path)
    kube.apply(cr.to_manifest())
    operator.reconcile()
    # scale down: parallelism 2 → 1
    cr2 = _app_cr(tmp_path, PIPELINE.replace("parallelism: 2", "parallelism: 1"))
    cr2.checksum = "c2"
    kube.apply(cr2.to_manifest())
    operator.reconcile()
    sts = kube.list("StatefulSet", "acme")
    assert sts[0]["spec"]["replicas"] == 1
    assert sts[0]["metadata"]["annotations"]["langstream.tpu/checksum"] == "c2"


def test_operator_marks_bad_app_error():
    kube = MockKubeApi()
    operator = Operator(kube)
    bad = ApplicationCustomResource(
        name="bad", namespace="acme",
        application={"modules": {"default": {"pipelines": {"p": {
            "agents": [{"type": "no-such-agent-type",
                        "input": "a", "output": "b"}]}}}}},
        instance={},
    )
    kube.apply(bad.to_manifest())
    operator.reconcile()  # must not raise
    doc = kube.get("Application", "acme", "bad")
    assert doc["status"]["phase"] == "ERROR"
    assert "no-such-agent-type" in doc["status"]["detail"]


def test_setup_job_manifest(tmp_path):
    job = generate_setup_job(_app_cr(tmp_path))
    assert job["kind"] == "Job"
    assert job["metadata"]["name"] == "demo-setup"
    command = job["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "application-setup" in command


def test_kubernetes_executor_composes_with_controlplane(tmp_path):
    asyncio.run(_test_kubernetes_executor(tmp_path))


async def _test_kubernetes_executor(tmp_path):
    from langstream_tpu.controlplane import (
        ApplicationService,
        GlobalMetadataStore,
        InMemoryApplicationStore,
        TenantService,
    )
    from langstream_tpu.controlplane.codestorage import InMemoryCodeStorage

    kube = MockKubeApi()
    operator = Operator(kube)
    executor = KubernetesExecutor(kube, operator)
    tenants = TenantService(GlobalMetadataStore())
    tenants.create("acme")
    service = ApplicationService(
        InMemoryApplicationStore(), InMemoryCodeStorage(), tenants,
        executor=executor,
    )
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("pipeline.yaml", PIPELINE)
    stored = await service.deploy("acme", "demo", buf.getvalue(), None)
    assert stored.status == "DEPLOYED"
    assert kube.list("StatefulSet", "acme")
    assert any("agent" in line for line in service.logs("acme", "demo"))
    await service.delete("acme", "demo")
    assert not kube.list("StatefulSet", "acme")
    assert not kube.list("Application", "acme")
