# Runtime image for langstream-tpu pods (reference:
# langstream-runtime/langstream-runtime-base-docker-image/src/main/docker/
# Dockerfile:12-22 — here a single Python image serves runner, deployer,
# setup, and code-download; the TPU runtime libs come from the base).
#
# Build:   docker build -t langstream-tpu/runtime:latest .
# On GKE TPU node pools use a base image with libtpu, e.g.
#   --build-arg BASE=python:3.12-slim          (CPU agents)
#   --build-arg BASE=<jax-tpu base image>      (TPU agents)
ARG BASE=python:3.12-slim
FROM ${BASE}

WORKDIR /app

COPY pyproject.toml README.md /app/
COPY langstream_tpu /app/langstream_tpu
COPY examples /app/examples

RUN pip install --no-cache-dir /app "jax[tpu]" || pip install --no-cache-dir /app

# the deployer's manifests invoke:
#   python -m langstream_tpu {agent-runner,code-download,application-setup,deployer}
# /app/config and /app/code are volume mounts (Secret + emptyDir)
ENV LANGSTREAM_CODE_DIR=/app/code \
    LANGSTREAM_STATE_DIR=/app/state \
    PYTHONUNBUFFERED=1

EXPOSE 8080 8000

ENTRYPOINT ["python", "-m", "langstream_tpu"]
CMD ["--help"]
