# Runtime image for langstream-tpu pods (reference:
# langstream-runtime/langstream-runtime-base-docker-image/src/main/docker/
# Dockerfile:12-22 — here a single Python image serves runner, deployer,
# setup, and code-download; the TPU runtime libs come from the base).
#
# Build:   docker build -t langstream-tpu/runtime:latest .
# On GKE TPU node pools use a base image with libtpu, e.g.
#   --build-arg BASE=python:3.12-slim          (CPU agents)
#   --build-arg BASE=<jax-tpu base image>      (TPU agents)
ARG BASE=python:3.12-slim
FROM ${BASE} AS runtime

WORKDIR /app

COPY pyproject.toml README.md /app/
COPY langstream_tpu /app/langstream_tpu
COPY examples /app/examples

RUN pip install --no-cache-dir /app "jax[tpu]" || pip install --no-cache-dir /app

# the deployer's manifests invoke:
#   python -m langstream_tpu {agent-runner,code-download,application-setup,deployer}
# /app/config and /app/code are volume mounts (Secret + emptyDir)
ENV LANGSTREAM_CODE_DIR=/app/code \
    LANGSTREAM_STATE_DIR=/app/state \
    PYTHONUNBUFFERED=1

EXPOSE 8080 8000

ENTRYPOINT ["python", "-m", "langstream_tpu"]
CMD ["--help"]

# ---------------------------------------------------------------------
# dev image: the runtime plus the machine-checked-invariant gate wired
# in as a git pre-commit hook (docs/analysis.md "Pre-commit hook").
#
#   docker build --target dev -t langstream-tpu/dev:latest .
#
# core.hooksPath is set globally, so ANY checkout mounted/cloned inside
# the container runs `langstream-tpu check --skip hlo` (lock discipline
# + jit hazards + retrace budget — seconds, no XLA compile) before a
# commit lands; CI's `analysis` shard still runs the full HLO matrix.
FROM runtime AS dev
COPY tools/githooks /app/tools/githooks
RUN apt-get update && apt-get install -y --no-install-recommends git \
    && rm -rf /var/lib/apt/lists/* \
    && chmod +x /app/tools/githooks/pre-commit \
    && git config --global core.hooksPath /app/tools/githooks
CMD ["check", "--skip", "hlo"]

# the DEFAULT build target must stay the runtime image: docker builds
# the LAST stage when no --target is given, and the documented
# `docker build -t langstream-tpu/runtime:latest .` (README) must not
# silently produce the dev image (git + pre-commit hook + check CMD)
FROM runtime
