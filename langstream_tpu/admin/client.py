"""Programmatic control-plane client.

Reference: ``langstream-admin-client/src/main/java/ai/langstream/admin/
client/AdminClient.java:42`` (HTTP client the CLI and operators embed:
applications().deploy/update/get/delete/logs, tenants()). Speaks to
``controlplane/webservice.py``'s REST surface; multipart deploy matches
the webservice's ``app``/``instance``/``secrets`` fields.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import aiohttp


class AdminClientError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class AdminClient:
    def __init__(
        self,
        base_url: str,
        *,
        tenant: str = "default",
        token: Optional[str] = None,
        timeout: float = 60.0,
        retries: int = 3,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.tenant = tenant
        self.token = token
        self.timeout = aiohttp.ClientTimeout(total=timeout)
        self.retries = max(1, retries)

    def _headers(self) -> Dict[str, str]:
        if self.token:
            return {"Authorization": f"Bearer {self.token}"}
        return {}

    async def _request(
        self,
        method: str,
        path: str,
        *,
        data: Any = None,
        json_body: Any = None,
        expect_bytes: bool = False,
        expect_text: bool = False,
        params: Optional[Dict[str, str]] = None,
    ) -> Any:
        import asyncio

        url = f"{self.base_url}{path}"
        # exponential retry for transient failures (reference:
        # admin-client ExponentialRetryPolicy): connection errors always
        # retry; HTTP 5xx retries only for idempotent reads — a deploy
        # that half-landed must not silently re-run
        idempotent = method in ("GET", "HEAD")
        last_error: Optional[BaseException] = None
        for attempt in range(self.retries):
            if attempt:
                await asyncio.sleep(0.2 * (2 ** (attempt - 1)))
            try:
                async with aiohttp.ClientSession(
                    timeout=self.timeout
                ) as session:
                    async with session.request(
                        method, url, data=data, json=json_body,
                        headers=self._headers(), params=params,
                    ) as response:
                        if response.status >= 400:
                            body = await response.text()
                            error = AdminClientError(response.status, body)
                            if response.status >= 500 and idempotent:
                                last_error = error
                                continue
                            raise error
                        if expect_bytes:
                            return await response.read()
                        if expect_text:
                            return await response.text()
                        return await response.json()
            except aiohttp.ClientConnectionError as error:
                if data is not None:
                    # multipart form data is consumed on first send and
                    # cannot be replayed — surface the failure
                    raise
                last_error = error
                continue
        raise last_error  # type: ignore[misc]

    # -- applications (reference: AdminClient.applications()) ----------- #
    async def deploy_application(
        self,
        application_id: str,
        archive: bytes,
        *,
        instance_yaml: Optional[str] = None,
        secrets_yaml: Optional[str] = None,
        update: bool = False,
        dry_run: bool = False,
    ) -> Dict[str, Any]:
        form = aiohttp.FormData()
        form.add_field("app", archive, filename="app.zip",
                       content_type="application/zip")
        if instance_yaml is not None:
            form.add_field("instance", instance_yaml)
        if secrets_yaml is not None:
            form.add_field("secrets", secrets_yaml)
        params = {"dry-run": "true"} if dry_run else None
        return await self._request(
            "PUT" if update else "POST",
            f"/api/applications/{self.tenant}/{application_id}",
            data=form, params=params,
        )

    async def deploy_application_directory(
        self, application_id: str, app_dir: str, **kwargs: Any
    ) -> Dict[str, Any]:
        """Zip an application directory client-side and deploy it; the
        sibling ``instance.yaml``/``secrets.yaml`` conventions match the
        reference CLI's ``apps deploy -app dir -i instance -s secrets``."""
        from langstream_tpu.controlplane.service import zip_directory

        archive = zip_directory(app_dir)
        return await self.deploy_application(
            application_id, archive, **kwargs
        )

    async def list_applications(self) -> List[Dict[str, Any]]:
        return await self._request("GET", f"/api/applications/{self.tenant}")

    async def get_application(self, application_id: str) -> Dict[str, Any]:
        return await self._request(
            "GET", f"/api/applications/{self.tenant}/{application_id}"
        )

    async def delete_application(self, application_id: str) -> Dict[str, Any]:
        return await self._request(
            "DELETE", f"/api/applications/{self.tenant}/{application_id}"
        )

    async def get_logs(self, application_id: str) -> str:
        return await self._request(
            "GET", f"/api/applications/{self.tenant}/{application_id}/logs",
            expect_text=True,
        )

    async def download_code(self, application_id: str) -> bytes:
        return await self._request(
            "GET", f"/api/applications/{self.tenant}/{application_id}/code",
            expect_bytes=True,
        )

    # -- tenants (reference: AdminClient.tenants()) --------------------- #
    async def list_tenants(self) -> Dict[str, Any]:
        return await self._request("GET", "/api/tenants")

    async def get_tenant(self, name: str) -> Dict[str, Any]:
        return await self._request("GET", f"/api/tenants/{name}")

    async def put_tenant(
        self, name: str, config: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        return await self._request(
            "PUT", f"/api/tenants/{name}", json_body=config or {}
        )

    async def delete_tenant(self, name: str) -> Dict[str, Any]:
        return await self._request("DELETE", f"/api/tenants/{name}")

    # -- archetypes ----------------------------------------------------- #
    async def list_archetypes(self) -> List[Dict[str, Any]]:
        return await self._request("GET", f"/api/archetypes/{self.tenant}")

    async def get_archetype(self, archetype_id: str) -> Dict[str, Any]:
        return await self._request(
            "GET", f"/api/archetypes/{self.tenant}/{archetype_id}"
        )

    async def deploy_from_archetype(
        self, archetype_id: str, application_id: str,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        return await self._request(
            "POST",
            f"/api/archetypes/{self.tenant}/{archetype_id}"
            f"/applications/{application_id}",
            json_body=parameters or {},
        )


# ---------------------------------------------------------------------- #
# CLI profiles (reference: langstream-cli profiles + ~/.langstream/config)
# ---------------------------------------------------------------------- #
DEFAULT_CONFIG_PATH = os.path.expanduser("~/.langstream-tpu/config.json")


def load_profiles(path: Optional[str] = None) -> Dict[str, Any]:
    import json

    path = path or os.environ.get(
        "LANGSTREAM_CLI_CONFIG", DEFAULT_CONFIG_PATH
    )
    if not os.path.exists(path):
        return {"profiles": {}, "current": None}
    with open(path, "r", encoding="utf-8") as handle:
        return json.load(handle)


def save_profiles(config: Dict[str, Any], path: Optional[str] = None) -> None:
    import json

    path = path or os.environ.get(
        "LANGSTREAM_CLI_CONFIG", DEFAULT_CONFIG_PATH
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as handle:
        json.dump(config, handle, indent=2)


def resolve_profile(
    profile: Optional[str] = None, path: Optional[str] = None
) -> Dict[str, Any]:
    """Pick the named (or current) profile; env vars win over the file
    (LANGSTREAM_API_URL / LANGSTREAM_TENANT / LANGSTREAM_TOKEN)."""
    config = load_profiles(path)
    name = profile or config.get("current")
    settings: Dict[str, Any] = {}
    if name and name in config.get("profiles", {}):
        settings = dict(config["profiles"][name])
    if os.environ.get("LANGSTREAM_API_URL"):
        settings["webServiceUrl"] = os.environ["LANGSTREAM_API_URL"]
    if os.environ.get("LANGSTREAM_TENANT"):
        settings["tenant"] = os.environ["LANGSTREAM_TENANT"]
    if os.environ.get("LANGSTREAM_TOKEN"):
        settings["token"] = os.environ["LANGSTREAM_TOKEN"]
    return settings


def client_from_profile(
    profile: Optional[str] = None,
    *,
    url: Optional[str] = None,
    tenant: Optional[str] = None,
    token: Optional[str] = None,
) -> AdminClient:
    settings = resolve_profile(profile)
    base_url = url or settings.get("webServiceUrl")
    if not base_url:
        raise SystemExit(
            "no control plane configured: pass --api-url, set "
            "LANGSTREAM_API_URL, or create a profile"
        )
    return AdminClient(
        base_url,
        tenant=tenant or settings.get("tenant", "default"),
        token=token or settings.get("token"),
    )
