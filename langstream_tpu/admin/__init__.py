from langstream_tpu.admin.client import AdminClient, AdminClientError

__all__ = ["AdminClient", "AdminClientError"]
