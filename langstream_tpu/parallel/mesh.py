"""Device mesh construction and logical-axis sharding.

Axes (the standard TPU serving/training decomposition):

- ``dp``   — data parallel (batch) — maps across hosts over DCN or chips.
- ``fsdp`` — parameter sharding for training (ZeRO-3 style).
- ``pp``   — pipeline parallel (layer stages, GPipe microbatch schedule
             in ``parallel.pipeline``) — rides DCN or outer ICI.
- ``tp``   — tensor parallel (heads / ffn) — must ride ICI.
- ``sp``   — sequence/context parallel (ring attention) — ICI.
- ``ep``   — expert parallel for MoE.

Parameters and activations are annotated with *logical* axis names
("vocab", "embed", "heads", "mlp", ...) and mapped to physical mesh axes by
the rules table — the MaxText/scaling-book recipe: pick a mesh, annotate,
let XLA insert collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @classmethod
    def from_config(cls, config: Optional[Dict[str, Any]]) -> "MeshConfig":
        if not config:
            return cls()
        return cls(
            dp=int(config.get("dp", 1)),
            fsdp=int(config.get("fsdp", 1)),
            pp=int(config.get("pp", config.get("pipeline-parallelism", 1))),
            tp=int(config.get("tp", config.get("tensor-parallelism", 1))),
            sp=int(config.get("sp", 1)),
            ep=int(config.get("ep", 1)),
        )

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.pp * self.tp * self.sp * self.ep

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.tp, self.sp, self.ep)


def validate_mesh(
    config: MeshConfig,
    *,
    num_heads: int,
    num_kv_heads: int,
    intermediate_size: int,
    num_experts: int = 0,
    num_layers: Optional[int] = None,
    allow_pp: bool = False,
) -> None:
    """Reject mesh/model combinations that would silently misbehave.

    Shared by the serving engine and the trainer so both fail with the
    same actionable errors instead of opaque XLA sharding diagnostics.
    """
    if config.tp > 1:
        for name, size in (
            ("num_kv_heads", num_kv_heads),
            ("num_heads", num_heads),
            ("intermediate_size", intermediate_size),
        ):
            if size % config.tp != 0:
                raise ValueError(f"tp={config.tp} must divide {name}={size}")
    if config.ep > 1:
        if not num_experts:
            raise ValueError(
                f"ep={config.ep} requires an MoE model (num_experts > 0); "
                "this model is dense"
            )
        if num_experts % config.ep != 0:
            raise ValueError(
                f"ep={config.ep} must divide num_experts={num_experts}"
            )
    if config.pp > 1:
        if not allow_pp:
            raise ValueError(
                f"pp={config.pp} is only supported by the pipeline trainer "
                "(parallel.pipeline); this component has no pipeline "
                "schedule — use tp/dp axes instead"
            )
        if num_layers is not None and num_layers % config.pp != 0:
            raise ValueError(
                f"pp={config.pp} must divide num_layers={num_layers}"
            )
        if config.fsdp > 1 or config.tp > 1 or config.sp > 1:
            # the pipeline shard_map only uses the pp and dp axes; other
            # axes would replicate params/activations and waste devices
            raise ValueError(
                f"pp={config.pp} composes only with dp for now "
                f"(got fsdp={config.fsdp}, tp={config.tp}, sp={config.sp})"
            )


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build the named mesh. With no config, all devices go to ``tp`` —
    the right default for single-host serving (ICI all-reduce)."""
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = MeshConfig(tp=len(devices))
    if config.size != len(devices):
        raise ValueError(
            f"mesh {config} needs {config.size} devices, have {len(devices)}"
        )
    array = np.asarray(devices).reshape(config.axis_sizes())
    return Mesh(array, MESH_AXES)


# logical axis → candidate physical axes (first that fits wins; None =
# replicated). Mirrors the MaxText-style sharding-rule table.
DEFAULT_RULES: Dict[str, Tuple[Optional[str], ...]] = {
    "batch": ("dp", "fsdp"),
    "sequence": ("sp",),
    "vocab": ("tp",),
    "embed": ("fsdp",),
    "heads": ("tp",),
    "kv_heads": ("tp",),
    "head_dim": (),
    "mlp": ("tp",),
    # the stacked-layer axis shards over pp ONLY when the pipeline engine
    # is driving (pp>1 meshes are used exclusively by parallel.pipeline);
    # on pp=1 meshes the rule is skipped and layers stay replicated
    "layers": ("pp",),
    "cache_batch": (),
    "cache_sequence": (),
    "expert": ("ep",),
}


def logical_to_physical(
    logical_axes: Sequence[Optional[str]],
    mesh: Mesh,
    rules: Optional[Dict[str, Tuple[Optional[str], ...]]] = None,
) -> PartitionSpec:
    """Map logical axis names to a PartitionSpec under the mesh, skipping
    axes whose mesh size is 1 (so the same annotations work from 1 chip to
    a full slice)."""
    rules = rules or DEFAULT_RULES
    used = set()
    spec: List[Optional[str]] = []
    for logical in logical_axes:
        chosen: Optional[str] = None
        if logical is not None:
            for candidate in rules.get(logical, ()):
                if candidate is None or candidate in used:
                    continue
                if mesh.shape.get(candidate, 1) > 1:
                    chosen = candidate
                    used.add(candidate)
                    break
        spec.append(chosen)
    return PartitionSpec(*spec)


class LogicalAxes:
    """Leaf-safe container of logical axis names for one parameter (a bare
    tuple would be traversed as a pytree container by ``jax.tree.map``)."""

    __slots__ = ("names",)

    def __init__(self, *names: Optional[str]) -> None:
        self.names = tuple(names)

    def __repr__(self) -> str:
        return f"L{self.names!r}"


L = LogicalAxes


def shard_params(params: Any, logical_axes: Any, mesh: Mesh, rules=None) -> Any:
    """Device-put a parameter pytree according to its logical-axes pytree
    (leaves of ``logical_axes`` are :class:`LogicalAxes`)."""

    def place(leaf, axes: LogicalAxes):
        spec = logical_to_physical(axes.names, mesh, rules)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, logical_axes)


def param_shardings(logical_axes: Any, mesh: Mesh, rules=None) -> Any:
    """NamedSharding pytree from a LogicalAxes pytree (for jit in/out
    shardings)."""

    def to_sharding(axes: LogicalAxes):
        return NamedSharding(mesh, logical_to_physical(axes.names, mesh, rules))

    return jax.tree.map(to_sharding, logical_axes)
