"""Ring attention: causal attention with the sequence sharded over a mesh
axis (context parallelism for long prompts).

Each device on the ``sp`` axis holds one contiguous chunk of the sequence
(q, k, v all [B, T/sp, H, D] locally). The kv chunks rotate around the
ring with ``jax.lax.ppermute`` while every device accumulates its local
queries' attention with the online-softmax recurrence — the same math as
the flash kernel (``ops/flash_attention.py``), but with the blocking axis
laid across chips instead of across VMEM tiles. XLA overlaps each
ppermute (ICI RDMA) with the previous step's matmuls, so the ring is
bandwidth-hidden once per-chunk compute exceeds the transfer.

The reference has no analogue — sequence length never spans processes
there (SURVEY §5 "long-context: ABSENT"); this is a net-new subsystem of
the TPU build, surfaced as the ``sp`` mesh axis of the jax-local provider.

Use :func:`ring_attention` inside ``shard_map`` (it needs a named axis) or
:func:`ring_attention_sharded` for the wrapped version.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _chunk_attend(
    q: jnp.ndarray,        # [B, Tq, KVH, G, D] grouped queries, f32 scores
    k: jnp.ndarray,        # [B, Tk, KVH, D]
    v: jnp.ndarray,        # [B, Tk, KVH, D]
    allowed: jnp.ndarray,  # [B, Tq, Tk] mask
    m: jnp.ndarray,        # [B, KVH, G, Tq, 1]
    l: jnp.ndarray,        # [B, KVH, G, Tq, 1]
    acc: jnp.ndarray,      # [B, Tq, KVH, G, D] f32
    scale: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax update of local queries against one kv chunk."""
    s = jnp.einsum(
        "bqkgd,bskd->bkgqs", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale  # [B, KVH, G, Tq, Tk]
    mask = allowed[:, None, None]  # [B, 1, 1, Tq, Tk]
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v).astype(
        jnp.float32
    )
    # alpha is [B, KVH, G, Tq, 1] → acc layout [B, Tq, KVH, G, D]
    alpha_acc = jnp.moveaxis(alpha, 3, 1)  # [B, Tq, KVH, G, 1]
    acc_new = acc * alpha_acc + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,  # local [B, Tl, H, D]
    k: jnp.ndarray,  # local [B, Tl, KVH, D]
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    axis_size: int,
    mask: Optional[jnp.ndarray] = None,  # local [B, Tl] valid-token mask
    causal: bool = True,
) -> jnp.ndarray:
    """Causal attention over the globally-sharded sequence. Must run inside
    ``shard_map`` over ``axis_name``; ``axis_size`` must be the static size
    of that axis (python loop bound — shapes are static under jit)."""
    batch, t_local, heads, dim = q.shape
    kv_heads = k.shape[2]
    groups = heads // kv_heads
    scale = dim ** -0.5
    my_idx = jax.lax.axis_index(axis_name)

    qg = q.reshape(batch, t_local, kv_heads, groups, dim)
    if mask is None:
        mask = jnp.ones((batch, t_local), dtype=bool)

    q_pos = my_idx * t_local + jnp.arange(t_local)  # [Tl] global positions

    m = jnp.full((batch, kv_heads, groups, t_local, 1), NEG_INF)
    l = jnp.zeros((batch, kv_heads, groups, t_local, 1))
    acc = jnp.zeros((batch, t_local, kv_heads, groups, dim))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    kv = (k, v, mask)
    for step in range(axis_size):
        # After `step` rotations we hold the kv chunk originally on device
        # (my_idx - step); its global key positions follow from that.
        src = (my_idx - step) % axis_size
        k_cur, v_cur, mask_cur = kv
        k_pos = src * t_local + jnp.arange(t_local)
        allowed = mask_cur[:, None, :]  # [B, 1, Tl] key validity
        if causal:
            allowed = jnp.logical_and(
                allowed, (k_pos[None, :] <= q_pos[:, None])[None]
            )
        else:
            allowed = jnp.broadcast_to(allowed, (batch, t_local, t_local))
        m, l, acc = _chunk_attend(qg, k_cur, v_cur, allowed, m, l, acc, scale)
        if step != axis_size - 1:
            kv = jax.lax.ppermute(kv, axis_name, perm)

    l_acc = jnp.moveaxis(l, 3, 1)  # [B, Tl, KVH, G, 1]
    out = acc / jnp.where(l_acc == 0.0, 1.0, l_acc)
    return out.reshape(batch, t_local, heads, dim).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,  # global [B, T, H, D]
    k: jnp.ndarray,  # global [B, T, KVH, D]
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    mask: Optional[jnp.ndarray] = None,  # global [B, T]
    causal: bool = True,
) -> jnp.ndarray:
    """Shard q/k/v's sequence axis over ``axis_name`` and run the ring."""
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size:
        raise ValueError(
            f"sequence {q.shape[1]} not divisible by {axis_name}={axis_size}"
        )
    qkv_spec = P(None, axis_name, None, None)
    mask_spec = P(None, axis_name)
    fn = functools.partial(
        ring_attention, axis_name=axis_name, axis_size=axis_size,
        causal=causal,
    )

    def wrapped(q, k, v, mask):
        return fn(q, k, v, mask=mask)

    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=bool)
    sharded = shard_map(
        wrapped, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return sharded(q, k, v, mask)
