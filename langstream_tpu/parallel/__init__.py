"""Mesh / sharding / collectives helpers — the distributed backbone.

The reference's "communication backend" is the broker plus NCCL-less remote
calls (SURVEY.md §2.5); model tensors never span processes. Here model
parallelism is first-class: a `jax.sharding.Mesh` over the TPU slice with
named axes, logical-axis sharding rules, and XLA collectives over ICI/DCN.
"""

from langstream_tpu.parallel.mesh import (
    L,
    LogicalAxes,
    MeshConfig,
    build_mesh,
    logical_to_physical,
    param_shardings,
    shard_params,
)

__all__ = [
    "L",
    "LogicalAxes",
    "MeshConfig",
    "build_mesh",
    "logical_to_physical",
    "param_shardings",
    "shard_params",
]
