"""Mesh / sharding / collectives helpers — the distributed backbone.

The reference's "communication backend" is the broker plus NCCL-less remote
calls (SURVEY.md §2.5); model tensors never span processes. Here model
parallelism is first-class: a `jax.sharding.Mesh` over the TPU slice with
named axes, logical-axis sharding rules, and XLA collectives over ICI/DCN.
"""

from langstream_tpu.parallel.mesh import (
    L,
    LogicalAxes,
    MeshConfig,
    build_mesh,
    logical_to_physical,
    param_shardings,
    shard_params,
)
from langstream_tpu.parallel.pipeline import (
    pipeline_apply,
    pipelined_logits,
    pipelined_loss_fn,
)
from langstream_tpu.parallel.ring import ring_attention, ring_attention_sharded
from langstream_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_sharded,
)

__all__ = [
    "L",
    "LogicalAxes",
    "MeshConfig",
    "build_mesh",
    "logical_to_physical",
    "param_shardings",
    "shard_params",
    "pipeline_apply",
    "pipelined_logits",
    "pipelined_loss_fn",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
