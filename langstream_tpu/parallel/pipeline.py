"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

The transformer's stacked-layer parameter arrays shard their leading
(layer) axis across the ``pp`` mesh axis, so each device holds a
contiguous *stage* of ``num_layers / pp`` layers. Inside ``shard_map``,
activations rotate stage→stage with ``jax.lax.ppermute`` while a
``lax.scan`` over ticks runs the classic GPipe schedule: at tick *i*,
stage *p* processes microbatch *i − p*; the pipe fills for ``pp − 1``
ticks, streams ``M`` microbatches, and drains. Everything is
differentiable (ppermute and scan have transpose rules), so one
``jax.value_and_grad`` over the whole pipelined loss gives the backward
pipeline for free — no hand-scheduled 1F1B needed; XLA overlaps the
ppermute transfers with each stage's matmuls.

The reference has no analogue — its only parallelism is replica data
parallelism over Kafka partitions (SURVEY §2.5: "TP / PP / SP / EP …
none exist in the reference"); pipeline parallelism is a net-new
subsystem of the TPU build for models too deep for one chip's HBM.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def pipeline_apply(
    stage_fn,                 # (stage_params, x [mb,...], mb_idx) -> (y, aux)
    stage_params: Any,        # pytree, LOCAL slice (inside shard_map)
    microbatches: jnp.ndarray,  # [M, mb, ...] (local dp shard)
    *,
    num_stages: int,
    axis: str = "pp",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the GPipe schedule. Must be called inside ``shard_map`` over
    ``axis``. ``stage_fn`` returns (activations, scalar aux); aux from
    every valid (stage, microbatch) pair is summed and psum-reduced over
    the pipe. Returns (outputs [M, mb, ...] valid on every device — the
    last stage's results are psum-broadcast — and the total aux)."""
    stage = jax.lax.axis_index(axis)
    num_mb = microbatches.shape[0]
    ticks = num_mb + num_stages - 1
    perm = [(p, (p + 1) % num_stages) for p in range(num_stages)]

    def tick_fn(carry, i):
        act, outputs, aux_sum = carry
        mb_idx = i - stage  # microbatch this stage works on this tick
        mb_safe = jnp.clip(mb_idx, 0, num_mb - 1)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(i, 0, num_mb - 1), 0, keepdims=False
        )
        x = jnp.where(stage == 0, inject, act)
        y, aux = stage_fn(stage_params, x, mb_safe)
        in_flight = (mb_idx >= 0) & (mb_idx < num_mb)
        aux_sum = aux_sum + jnp.where(in_flight, aux, 0.0)
        # collect finished microbatches on the last stage
        valid = (stage == num_stages - 1) & in_flight
        sel = (jnp.arange(num_mb) == mb_safe) & valid
        outputs = jnp.where(
            sel.reshape((num_mb,) + (1,) * (y.ndim)), y[None], outputs
        )
        act = jax.lax.ppermute(y, axis, perm)
        return (act, outputs, aux_sum), None

    act0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    aux0 = jnp.zeros((), dtype=jnp.float32)
    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick_fn, (act0, outputs0, aux0), jnp.arange(ticks)
    )
    # broadcast the last stage's collected outputs to every stage
    is_last = stage == num_stages - 1
    outputs = jax.lax.psum(
        jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
    )
    aux_sum = jax.lax.psum(aux_sum, axis)
    return outputs, aux_sum


def pipelined_logits(
    config,
    params,
    tokens: jnp.ndarray,   # [B, T]
    mask: Optional[jnp.ndarray],
    freqs: jnp.ndarray,
    mesh: Mesh,
    num_microbatches: int,
    with_aux: bool = False,
) -> jnp.ndarray:
    """Full-model forward with the layer stack pipelined over ``pp``.

    Embedding/final-norm/lm-head stay replicated outside the shard_map;
    only the layer stack runs in the pipeline. Microbatches additionally
    shard over ``dp`` when that axis is present (each dp group runs its
    own independent pipeline). Returns logits [B, T, V]; with
    ``with_aux`` also the mean MoE load-balancing loss.
    """
    from langstream_tpu.providers.jax_local import model as model_lib

    num_stages = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    if config.num_layers % num_stages:
        raise ValueError(
            f"pp={num_stages} must divide num_layers={config.num_layers}"
        )
    batch, seq = tokens.shape
    if batch % num_microbatches:
        raise ValueError(
            f"microbatches={num_microbatches} must divide batch={batch}"
        )
    mb = batch // num_microbatches
    if mb % dp:
        raise ValueError(
            f"dp={dp} must divide the microbatch size {mb} "
            f"(batch {batch} / microbatches {num_microbatches})"
        )

    x = model_lib._embed(config, params, tokens)  # [B, T, H]
    xs = x.reshape(num_microbatches, mb, seq, config.hidden_size)
    if mask is None:
        mask = jnp.ones((batch, seq), dtype=bool)
    masks = mask.reshape(num_microbatches, mb, seq)
    layer_inputs = model_lib._stack_layer_params(params, config)
    # per-layer sliding windows ride the SAME pp sharding as the layer
    # stack, so each stage receives ITS layers' windows — a static
    # offset cannot vary across SPMD stages (Gemma-2 alternates
    # sliding/full per GLOBAL layer index). Zeros = full attention.
    windows = model_lib.layer_windows(config)
    if windows is None:
        windows = jnp.zeros((config.num_layers,), dtype=jnp.int32)

    def stage_fn_inner(stage_layers, stage_windows, x, mb_idx, masks, freqs):
        m = jax.lax.dynamic_index_in_dim(masks, mb_idx, 0, keepdims=False)
        return model_lib.apply_layers(
            config, stage_layers, x, m, freqs, windows=stage_windows
        )

    def pipelined(stage_layers, stage_windows, xs, masks, freqs):
        outs, aux = pipeline_apply(
            lambda sp, x, i: stage_fn_inner(
                sp, stage_windows, x, i, masks, freqs
            ),
            stage_layers, xs, num_stages=num_stages,
        )
        # aux differs per dp group (different data): reduce it so the
        # P() out_spec (replicated) is truthful
        return outs, jax.lax.psum(aux, "dp")

    layer_specs = jax.tree.map(lambda _: P("pp"), layer_inputs)
    data_spec = P(None, "dp")  # microbatch rows shard over dp
    fn = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(layer_specs, P("pp"), data_spec, data_spec, P()),
        out_specs=(data_spec, P()),
        check_vma=False,
    )
    outs, aux = fn(
        layer_inputs, windows, xs, masks, freqs
    )  # [M, mb, T, H], scalar

    x = outs.reshape(batch, seq, config.hidden_size)
    x = model_lib._norm(config, x, params["final_norm"])
    logits = model_lib._logits(config, params, x)
    if with_aux:
        # aux was summed over layers × microbatches (and psum'd over dp
        # copies of the pipe); normalize to the per-layer mean like
        # model.forward(with_aux=True)
        aux = aux / max(config.num_layers * num_microbatches * dp, 1)
        return logits, aux
    return logits


def pipelined_loss_fn(
    config, params, tokens, mask, freqs, mesh, num_microbatches,
    moe_aux_weight: float = 0.0,
) -> jnp.ndarray:
    """Causal next-token cross-entropy over the pipelined forward (the
    pp-mesh counterpart of ``training.trainer.loss_fn``), including the
    MoE load-balancing aux term for MoE models."""
    from langstream_tpu.ops.losses import causal_ce_loss

    if mask is None:
        mask = jnp.ones(tokens.shape, dtype=bool)
    if config.num_experts and moe_aux_weight:
        logits, aux = pipelined_logits(
            config, params, tokens, mask, freqs, mesh, num_microbatches,
            with_aux=True,
        )
        return causal_ce_loss(logits, tokens, mask) + moe_aux_weight * aux
    logits = pipelined_logits(
        config, params, tokens, mask, freqs, mesh, num_microbatches
    )
    return causal_ce_loss(logits, tokens, mask)
