"""Ulysses sequence parallelism: all-to-all head-sharded attention.

The complement to ring attention (``parallel.ring``) for long contexts:
instead of rotating KV chunks around the ``sp`` ring, two
``jax.lax.all_to_all`` collectives re-shard the activations from
*sequence-sharded* ([B, T/sp, H, D]) to *head-sharded* ([B, T, H/sp, D]),
run ordinary full-sequence attention locally on each device's head
slice, and swap back. Communication volume is 2 all-to-alls of the
activations per attention — independent of sequence length per device —
versus the ring's ``sp − 1`` KV rotations; Ulysses wins when heads ≥ sp
and the per-chunk compute is too small to hide the ring latency
(short-to-medium contexts, decode), the ring wins when sp exceeds the
head count or memory forbids full-T scores.

The reference has no analogue (SURVEY §5 "long-context … ABSENT"); this
is a net-new subsystem of the TPU build, selected via the jax-local
provider's ``sp`` mesh axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from langstream_tpu.ops.attention import prefill_attention


def ulysses_attention(
    q: jnp.ndarray,  # [B, T/sp, NH, D]  local sequence shard
    k: jnp.ndarray,  # [B, T/sp, NKV, D]
    v: jnp.ndarray,  # [B, T/sp, NKV, D]
    mask: Optional[jnp.ndarray] = None,  # [B, T] FULL-length valid mask
    axis: str = "sp",
) -> jnp.ndarray:
    """Causal attention with sequence sharded over ``axis``; must run
    inside ``shard_map``. Head counts must be divisible by the axis size.
    Returns the local sequence shard of the attention output."""
    sp = jax.lax.psum(1, axis)
    if q.shape[2] % sp or k.shape[2] % sp:
        raise ValueError(
            f"ulysses needs heads divisible by sp={sp}: "
            f"q heads {q.shape[2]}, kv heads {k.shape[2]}"
        )
    # seq-sharded → head-sharded: split heads (axis 2), gather seq (axis 1)
    qg = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kg = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vg = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    out = prefill_attention(qg, kg, vg, mask=mask)  # [B, T, NH/sp, D]
    # head-sharded → seq-sharded: split seq, gather heads
    return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(
    q: jnp.ndarray,  # [B, T, NH, D] global arrays
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    mask: Optional[jnp.ndarray] = None,
    axis: str = "sp",
) -> jnp.ndarray:
    """Jit-callable wrapper: shards the sequence axis over ``axis`` of
    ``mesh`` and runs :func:`ulysses_attention`."""
    seq_spec = P(None, axis, None, None)
    mask_spec = P()
    fn = shard_map(
        lambda q, k, v, m: ulysses_attention(q, k, v, m, axis=axis),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, mask_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=bool)
    return fn(q, k, v, mask)
