"""Application directory parser.

Equivalent of the reference's ``ModelBuilder``
(``langstream-core/src/main/java/ai/langstream/impl/parser/ModelBuilder.java:74``;
file dispatch at 410-465, pipelines 659, secrets 812, instance 837): an
application is a directory of YAML files —

- ``configuration.yaml``   — ``configuration.resources`` + ``dependencies``
- ``gateways.yaml``        — gateway endpoint list
- ``instance.yaml``        — clusters + globals (may live outside the dir)
- ``secrets.yaml``         — secret id → data map (env-expanded)
- every other ``*.yaml``   — a pipeline file: ``topics:`` + ``pipeline:``
  (+ optional ``errors:`` defaults, ``module:``, ``name:``, ``id:``)
- ``python/``              — user agent code, put on ``sys.path`` at run
  (the reference mounts it into the gRPC runtime's PYTHONPATH,
  ``PythonGrpcServer.java:54-91``)
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional

import yaml

from langstream_tpu.api.errors import ErrorsSpec
from langstream_tpu.model.application import (
    DEFAULT_MODULE,
    AgentConfiguration,
    Application,
    AssetDefinition,
    Gateway,
    Instance,
    Module,
    Pipeline,
    Secrets,
    TopicDefinition,
)
from langstream_tpu.compiler.placeholders import (
    build_context,
    resolve_env,
    resolve_value,
)

_SPECIAL_FILES = {"configuration", "gateways", "instance", "secrets"}


def _load_yaml(path: str) -> Any:
    with open(path, "r", encoding="utf-8") as handle:
        return yaml.safe_load(handle)


def parse_pipeline_file(
    application: Application, file_name: str, content: Dict[str, Any]
) -> None:
    """One pipeline file → topics + a Pipeline in its module
    (``ModelBuilder.parsePipelineFile``, line 659)."""
    if content is None:
        return
    module_id = content.get("module", DEFAULT_MODULE)
    module = application.module(module_id)
    pipeline_id = content.get("id") or os.path.splitext(os.path.basename(file_name))[0]
    pipeline = Pipeline(
        id=pipeline_id,
        module=module_id,
        name=content.get("name"),
        errors=ErrorsSpec.from_config(content.get("errors")),
    )
    for topic_config in content.get("topics", []) or []:
        topic = TopicDefinition.from_config(topic_config)
        module.topics[topic.name] = topic
    for asset_config in content.get("assets", []) or []:
        asset = AssetDefinition.from_config(asset_config)
        module.assets[asset.id] = asset
    used_ids = set()
    for index, agent_config in enumerate(content.get("pipeline", []) or []):
        agent = AgentConfiguration.from_config(agent_config)
        if agent.id is None:
            # deterministic auto-id, mirroring the reference's generated ids
            base = (agent.name or agent.type).lower().replace(" ", "-")
            agent.id = base if base not in used_ids else f"{base}-{index}"
        used_ids.add(agent.id)
        agent.errors = agent.errors.with_defaults_from(pipeline.errors)
        pipeline.agents.append(agent)
    module.pipelines[pipeline.id] = pipeline


def parse_configuration_file(application: Application, content: Dict[str, Any]) -> None:
    configuration = (content or {}).get("configuration", {}) or {}
    for resource in configuration.get("resources", []) or []:
        name = resource.get("id") or resource.get("name") or resource.get("type")
        application.resources[name] = resource
    application.dependencies = configuration.get("dependencies", []) or []


def parse_gateways_file(application: Application, content: Dict[str, Any]) -> None:
    for gateway_config in (content or {}).get("gateways", []) or []:
        application.gateways.append(Gateway.from_config(gateway_config))


def parse_secrets(content: Dict[str, Any]) -> Secrets:
    """``secrets.yaml`` (``ModelBuilder.parseSecrets``, line 812); values are
    env-expanded (``${VAR:-default}``)."""
    out: Dict[str, Dict[str, Any]] = {}
    for secret in (content or {}).get("secrets", []) or []:
        data = secret.get("data", {}) or {}
        out[secret["id"]] = {
            key: resolve_env(value) if isinstance(value, str) else value
            for key, value in data.items()
        }
    return Secrets(secrets=out)


def parse_instance(content: Dict[str, Any]) -> Instance:
    return Instance.from_config((content or {}).get("instance", {}) or {})


def parse_application_directory(
    app_dir: str,
    *,
    instance_file: Optional[str] = None,
    secrets_file: Optional[str] = None,
) -> Application:
    """Parse without placeholder resolution (see :func:`build_application`)."""
    application = Application(application_id=os.path.basename(os.path.normpath(app_dir)))
    names = sorted(os.listdir(app_dir))
    for name in names:
        path = os.path.join(app_dir, name)
        if not name.endswith((".yaml", ".yml")) or not os.path.isfile(path):
            continue
        content = _load_yaml(path)
        stem = os.path.splitext(name)[0]
        if stem == "configuration":
            parse_configuration_file(application, content)
        elif stem == "gateways":
            parse_gateways_file(application, content)
        elif stem == "instance":
            application.instance = parse_instance(content)
        elif stem == "secrets":
            application.secrets = parse_secrets(content)
        elif stem == "archetype":
            pass  # archetype manifest (metadata only, not a pipeline)
        else:
            parse_pipeline_file(application, name, content)
    if instance_file:
        application.instance = parse_instance(_load_yaml(instance_file))
    if secrets_file:
        application.secrets = parse_secrets(_load_yaml(secrets_file))
    python_dir = os.path.join(app_dir, "python")
    if os.path.isdir(python_dir):
        application.python_path = python_dir
    return application


def resolve_placeholders(application: Application) -> Application:
    """Interpolate ``${secrets.*}`` / ``${globals.*}`` / ``${cluster.*}``
    across resources, agent configurations, and gateways
    (``ApplicationPlaceholderResolver.java:45``)."""
    context = build_context(
        application.secrets.secrets,
        application.instance.globals_,
        application.instance.streaming_cluster.get("configuration", {}) or {},
    )
    application.resources = resolve_value(application.resources, context)
    for module in application.modules.values():
        for pipeline in module.pipelines.values():
            for agent in pipeline.agents:
                agent.configuration = resolve_value(agent.configuration, context)
    for gateway in application.gateways:
        gateway.authentication = resolve_value(gateway.authentication, context)
        gateway.produce_options = resolve_value(gateway.produce_options, context)
        gateway.consume_options = resolve_value(gateway.consume_options, context)
        gateway.chat_options = resolve_value(gateway.chat_options, context)
    return application


def build_application(
    app_dir: str,
    *,
    instance_file: Optional[str] = None,
    secrets_file: Optional[str] = None,
) -> Application:
    """Parse + resolve: the equivalent of
    ``ModelBuilder.buildApplicationInstance`` (``ModelBuilder.java:370``)."""
    application = parse_application_directory(
        app_dir, instance_file=instance_file, secrets_file=secrets_file
    )
    return resolve_placeholders(application)


def application_checksum(app_dir: str) -> str:
    """Content checksum for change detection (the reference computes
    py/java checksums in ``ModelBuilder``, DTOs at 877-940)."""
    digest = hashlib.sha256()
    for root, _dirs, files in sorted(os.walk(app_dir)):
        for name in sorted(files):
            path = os.path.join(root, name)
            digest.update(os.path.relpath(path, app_dir).encode())
            with open(path, "rb") as handle:
                digest.update(handle.read())
    return digest.hexdigest()
