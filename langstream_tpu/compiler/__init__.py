"""The compiler: YAML application directory → Application → ExecutionPlan.

Equivalent of the reference's parser/planner pair
(``langstream-core/src/main/java/ai/langstream/impl/parser/ModelBuilder.java:74``
and ``impl/common/BasicClusterRuntime.java:45``).
"""

from langstream_tpu.compiler.parser import build_application, parse_application_directory
from langstream_tpu.compiler.planner import ExecutionPlan, build_execution_plan

__all__ = [
    "ExecutionPlan",
    "build_application",
    "build_execution_plan",
    "parse_application_directory",
]
