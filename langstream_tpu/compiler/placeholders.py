"""Placeholder resolution: ``${secrets.*}``, ``${globals.*}``, env defaults.

Equivalent of the reference's resolver
(``langstream-core/src/main/java/ai/langstream/impl/common/ApplicationPlaceholderResolver.java:45``):
after parsing, every string in the model is interpolated against a context
of ``secrets`` / ``globals`` / ``cluster`` values. Secrets *values* may
themselves use shell-style env expansion ``${ENV_VAR:-default}``
(``examples/secrets/secrets.yaml:18-30``).

Mustache prompt templates (``{{ value.question }}``) are NOT resolved here —
they are runtime templates owned by the chat-completions step.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict

_PLACEHOLDER = re.compile(
    r"\$\{\s*([a-zA-Z0-9_.\-]+)\s*(?::-([^}]*))?\}"
)
_ENV = re.compile(r"\$\{(?P<name>[A-Za-z_][A-Za-z0-9_]*)(?::-(?P<default>[^}]*))?\}")


class PlaceholderError(KeyError):
    pass


def resolve_env(value: str) -> str:
    """Shell-style ``${VAR}`` / ``${VAR:-default}`` expansion (secrets files)."""

    def sub(match: "re.Match[str]") -> str:
        name = match.group("name")
        default = match.group("default")
        got = os.environ.get(name)
        if got is not None:
            return got
        if default is not None:
            return default
        raise PlaceholderError(f"environment variable {name} not set")

    return _ENV.sub(sub, value)


def _lookup(
    context: Dict[str, Any], dotted: str, default: Any = None,
    has_default: bool = False,
) -> Any:
    node: Any = context
    for part in dotted.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            if has_default:
                # ``${globals.key:-fallback}`` — same shell-style default
                # spelling secrets values already support (resolve_env)
                return default
            raise PlaceholderError(f"unresolved placeholder: ${{{dotted}}}")
    return node


def resolve_value(value: Any, context: Dict[str, Any]) -> Any:
    if isinstance(value, str):
        # whole-string placeholder keeps the native type of the target
        whole = _PLACEHOLDER.fullmatch(value.strip())
        if whole:
            return _lookup(
                context, whole.group(1), whole.group(2),
                has_default=whole.group(2) is not None,
            )

        def sub(match: "re.Match[str]") -> str:
            return str(_lookup(
                context, match.group(1), match.group(2),
                has_default=match.group(2) is not None,
            ))

        return _PLACEHOLDER.sub(sub, value)
    if isinstance(value, dict):
        return {k: resolve_value(v, context) for k, v in value.items()}
    if isinstance(value, list):
        return [resolve_value(v, context) for v in value]
    return value


def build_context(
    secrets: Dict[str, Dict[str, Any]],
    globals_: Dict[str, Any],
    cluster: Dict[str, Any],
) -> Dict[str, Any]:
    """Context shape per the reference (``ApplicationPlaceholderResolver``
    context build, lines 81-92): ``secrets.<id>.<key>``, ``globals.<key>``,
    ``cluster.<key>``."""
    return {"secrets": secrets, "globals": globals_, "cluster": cluster}
