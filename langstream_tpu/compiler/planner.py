"""The planner: Application → ExecutionPlan.

Equivalent of the reference's generic planner
(``langstream-core/src/main/java/ai/langstream/impl/common/BasicClusterRuntime.java:45``:
buildExecutionPlan 50-66, detectAgents 121-146, buildAgent+merge 158-254)
plus the composable-agent fusion optimiser
(``impl/agents/ComposableAgentExecutionPlanOptimiser.java:34``) and the
GenAI-toolkit step mapping
(``impl/agents/ai/GenAIToolKitFunctionAgentProvider.java:51``, STEP_TYPES
53-74, steps assembly 117-163).

Walk each pipeline in order; each agent either *fuses* with the previous one
(no explicit topic between them, same resources → one node, records passed
in memory) or is separated by a topic (explicit, or an implicit
``create-if-not-exists`` intermediate). Declarative GenAI step types
(``drop-fields``, ``compute``, ``ai-chat-completions``, ...) all compile to
one ``ai-tools`` executable whose config is a ``steps`` list; consecutive
steps merge into the same executable exactly like the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import ComponentType
from langstream_tpu.api.errors import ErrorsSpec
from langstream_tpu.api.topics import TopicSpec
from langstream_tpu.model.application import (
    AgentConfiguration,
    Application,
    Pipeline,
    ResourcesSpec,
    TopicDefinition,
)

# Declarative step types that compile onto the single GenAI toolkit executor
# (GenAIToolKitFunctionAgentProvider.java:53-74).
GENAI_STEP_TYPES = {
    "drop-fields",
    "merge-key-value",
    "unwrap-key-value",
    "cast",
    "flatten",
    "drop",
    "compute",
    "compute-ai-embeddings",
    "query",
    "ai-chat-completions",
    "ai-text-completions",
}

# Planner-side kind table for built-in types, so planning does not need to
# instantiate agents (the reference declares kinds in per-agent planning
# providers under langstream-k8s-runtime/.../agents/).
_KIND: Dict[str, ComponentType] = {
    "identity": ComponentType.PROCESSOR,
    "composite-agent": ComponentType.PROCESSOR,
    "ai-tools": ComponentType.PROCESSOR,
    "python-processor": ComponentType.PROCESSOR,
    "text-splitter": ComponentType.PROCESSOR,
    "document-to-json": ComponentType.PROCESSOR,
    "text-normaliser": ComponentType.PROCESSOR,
    "language-detector": ComponentType.PROCESSOR,
    "text-extractor": ComponentType.PROCESSOR,
    "dispatch": ComponentType.PROCESSOR,
    "trigger-event": ComponentType.PROCESSOR,
    "log-event": ComponentType.PROCESSOR,
    "http-request": ComponentType.PROCESSOR,
    "query-vector-db": ComponentType.PROCESSOR,
    "re-rank": ComponentType.PROCESSOR,
    "python-source": ComponentType.SOURCE,
    "timer-source": ComponentType.SOURCE,
    "webcrawler-source": ComponentType.SOURCE,
    "s3-source": ComponentType.SOURCE,
    "file-source": ComponentType.SOURCE,
    "azure-blob-storage-source": ComponentType.SOURCE,
    "exec-source": ComponentType.SOURCE,
    "kafka-connect-source": ComponentType.SOURCE,
    "python-sink": ComponentType.SINK,
    "vector-db-sink": ComponentType.SINK,
    "exec-sink": ComponentType.SINK,
    "kafka-connect-sink": ComponentType.SINK,
    "python-service": ComponentType.SERVICE,
}


def agent_kind(agent_type: str) -> ComponentType:
    if agent_type in GENAI_STEP_TYPES:
        return ComponentType.PROCESSOR
    kind = _KIND.get(agent_type)
    if kind is not None:
        return kind
    # custom/unknown types: fall back to instantiating via the registry
    from langstream_tpu.runtime.registry import create_agent

    return create_agent(agent_type).component_type()


@dataclasses.dataclass
class AgentSpec:
    """Executable description of one (sub-)agent inside a node."""

    agent_id: str
    agent_type: str
    configuration: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_config(self) -> Dict[str, Any]:
        return {
            "agentId": self.agent_id,
            "agentType": self.agent_type,
            "configuration": self.configuration,
        }


@dataclasses.dataclass
class AgentNode:
    """One execution-plan node = one runner (pod) holding a fused
    source? + processors + sink? chain
    (reference: ``runtime/AgentNode.java:22`` + composite merge)."""

    id: str
    pipeline: str
    module: str
    source: Optional[AgentSpec] = None
    processors: List[AgentSpec] = dataclasses.field(default_factory=list)
    sink: Optional[AgentSpec] = None
    service: Optional[AgentSpec] = None
    input_topic: Optional[str] = None
    output_topic: Optional[str] = None
    errors: ErrorsSpec = dataclasses.field(default_factory=ErrorsSpec)
    resources: ResourcesSpec = dataclasses.field(default_factory=ResourcesSpec)

    def all_agent_ids(self) -> List[str]:
        out = []
        for spec in [self.source, *self.processors, self.sink, self.service]:
            if spec is not None:
                out.append(spec.agent_id)
        return out


@dataclasses.dataclass
class ExecutionPlan:
    """Topics + assets + agent nodes
    (``langstream-api/.../runtime/ExecutionPlan.java:32``, maps 18-20)."""

    application: Application
    topics: Dict[str, TopicSpec] = dataclasses.field(default_factory=dict)
    agents: List[AgentNode] = dataclasses.field(default_factory=list)
    assets: List[Any] = dataclasses.field(default_factory=list)

    def agent(self, node_id: str) -> AgentNode:
        for node in self.agents:
            if node.id == node_id:
                return node
        raise KeyError(node_id)


def _topic_spec(topic: TopicDefinition) -> TopicSpec:
    return TopicSpec(
        name=topic.name,
        partitions=topic.partitions,
        creation_mode=topic.creation_mode,
        deletion_mode=topic.deletion_mode,
        options=topic.options,
        config=topic.config,
        implicit=topic.implicit,
        schema=topic.schema,
    )


def _to_executable(agent: AgentConfiguration) -> AgentSpec:
    """Map a declared agent type to its executable spec; GenAI step types
    compile to the ``ai-tools`` executor with a one-step ``steps`` list."""
    if agent.type in GENAI_STEP_TYPES:
        step = {"type": agent.type, **agent.configuration}
        return AgentSpec(
            agent_id=agent.id or agent.type,
            agent_type="ai-tools",
            configuration={"steps": [step]},
        )
    return AgentSpec(
        agent_id=agent.id or agent.type,
        agent_type=agent.type,
        configuration=dict(agent.configuration),
    )


def _can_fuse(
    previous: AgentConfiguration, current: AgentConfiguration
) -> bool:
    """Fusion rule (``ComposableAgentExecutionPlanOptimiser.canMerge``,
    line 42): no explicit topic between them, identical resources, and
    identical error policy (a fused node has one policy; differing specs
    must keep their own node so each agent's ``errors:`` is honored)."""
    if previous.output is not None or current.input is not None:
        return False
    if previous.resources != current.resources:
        return False
    if previous.errors != current.errors:
        return False
    if agent_kind(current.type) not in (ComponentType.PROCESSOR, ComponentType.SINK):
        return False
    return True


def _build_pipeline_nodes(
    plan: ExecutionPlan, pipeline: Pipeline, application: Application
) -> None:
    module = application.modules[pipeline.module]
    nodes: List[AgentNode] = []
    # open_node: node still accepting fusion; prev_agent: its last agent
    open_node: Optional[AgentNode] = None
    prev_agent: Optional[AgentConfiguration] = None
    # topic the next agent consumes when it declares no input (set when a
    # node was sealed by an explicit `output:`)
    pending_input: Optional[str] = None

    def ensure_topic(name: str, implicit: bool = False) -> None:
        if name in plan.topics:
            return
        definition = module.topics.get(name)
        if definition is None:
            if not implicit:
                raise ValueError(
                    f"pipeline {pipeline.id!r} references undeclared topic {name!r}"
                )
            definition = TopicDefinition(
                name=name, creation_mode="create-if-not-exists", implicit=True
            )
            module.topics[name] = definition
        plan.topics[name] = _topic_spec(definition)

    def new_node(agent: AgentConfiguration, **fields) -> AgentNode:
        node = AgentNode(
            id=agent.id or agent.type,
            pipeline=pipeline.id,
            module=pipeline.module,
            errors=agent.errors,
            resources=agent.resources,
            **fields,
        )
        nodes.append(node)
        return node

    for agent in pipeline.agents:
        kind = agent_kind(agent.type)
        executable = _to_executable(agent)

        if kind is ComponentType.SERVICE:
            new_node(agent, service=executable)
            open_node, prev_agent, pending_input = None, None, None
            continue

        if kind is ComponentType.SOURCE:
            # a source always heads a fresh node; a still-open upstream node
            # stays terminal (no output topic)
            open_node = new_node(agent, source=executable)
            prev_agent = agent
        elif (
            open_node is not None
            and prev_agent is not None
            and _can_fuse(prev_agent, agent)
        ):
            _attach_fused(open_node, kind, executable)
            prev_agent = agent
        else:
            input_topic = agent.input
            if open_node is not None and prev_agent is not None:
                # seal the open node with a boundary topic the new node reads
                boundary = input_topic or f"{pipeline.id}-{agent.id}-input"
                ensure_topic(boundary, implicit=input_topic is None)
                open_node.output_topic = boundary
                input_topic = boundary
            elif input_topic is None:
                input_topic = pending_input
            if input_topic is None:
                raise ValueError(
                    f"agent {agent.id!r} in pipeline {pipeline.id!r} has no "
                    "input topic and no upstream agent"
                )
            ensure_topic(input_topic)
            open_node = new_node(agent, input_topic=input_topic)
            _attach(open_node, kind, executable)
            prev_agent = agent

        pending_input = None
        if agent.output is not None:
            ensure_topic(agent.output)
            open_node.output_topic = agent.output
            pending_input = agent.output
            open_node, prev_agent = None, None
        elif kind is ComponentType.SINK:
            # a custom sink terminates its node
            open_node, prev_agent = None, None

    plan.agents.extend(nodes)


def _attach(node: AgentNode, kind: ComponentType, spec: AgentSpec) -> None:
    if kind is ComponentType.PROCESSOR:
        node.processors.append(spec)
    elif kind is ComponentType.SINK:
        node.sink = spec
    elif kind is ComponentType.SOURCE:
        node.source = spec


def _attach_fused(node: AgentNode, kind: ComponentType, spec: AgentSpec) -> None:
    """Merge into an open node; consecutive ``ai-tools`` merge their step
    lists into one executor (GenAIToolKitFunctionAgentProvider steps
    assembly, 117-163)."""
    if (
        kind is ComponentType.PROCESSOR
        and spec.agent_type == "ai-tools"
        and node.processors
        and node.processors[-1].agent_type == "ai-tools"
    ):
        node.processors[-1].configuration["steps"].extend(
            spec.configuration["steps"]
        )
        return
    _attach(node, kind, spec)


def build_execution_plan(application: Application) -> ExecutionPlan:
    """``ComputeClusterRuntime.buildExecutionPlan`` equivalent
    (``langstream-api/.../runtime/ComputeClusterRuntime.java:32``)."""
    plan = ExecutionPlan(application=application)
    _validate_agent_configs(application)
    # declared topics first (even if no agent references them: gateways may)
    for module in application.modules.values():
        for topic in module.topics.values():
            plan.topics.setdefault(topic.name, _topic_spec(topic))
        plan.assets.extend(module.assets.values())
        for pipeline in module.pipelines.values():
            _build_pipeline_nodes(plan, pipeline, application)
    _validate(plan)
    return plan


def _validate_agent_configs(application: Application) -> None:
    """Typed config validation against the doc model BEFORE any planner
    transforms (reference: ``ClassConfigValidator.java:60`` runs on the
    raw agent configuration)."""
    from langstream_tpu.model.docs import validate_agent_config

    errors = []
    for module in application.modules.values():
        for pipeline in module.pipelines.values():
            for agent in pipeline.agents:
                errors.extend(
                    validate_agent_config(agent.type, agent.configuration)
                )
                if agent.type == "camel-source":
                    # unsupported Camel URIs must fail AT PLAN TIME with
                    # the scheme list + exec-bridge recipe, not when the
                    # pod boots (reference escape hatch: CamelSource
                    # accepts any URI because it has the whole JVM zoo)
                    from langstream_tpu.agents.camel import (
                        validate_component_uri,
                    )

                    options = agent.configuration.get("component-options")
                    problem = validate_component_uri(
                        str(agent.configuration.get("component-uri") or ""),
                        options if isinstance(options, dict) else None,
                        expect_plugin_scheme=str(
                            agent.configuration.get(
                                "expect-plugin-scheme", ""
                            )
                        ).lower() in ("1", "true", "yes"),
                    )
                    if problem:
                        # most validator messages arrive already
                        # prefixed ("camel-source: kafka URI needs a
                        # topic name") — re-prefixing those yields
                        # "camel-source: camel-source: ..." (ADVICE r5)
                        errors.append(
                            problem
                            if problem.startswith("camel-source:")
                            else f"camel-source: {problem}"
                        )
    if errors:
        raise ValueError(
            "invalid agent configuration:\n  " + "\n  ".join(errors)
        )


def _validate(plan: ExecutionPlan) -> None:
    seen = set()
    for node in plan.agents:
        if node.id in seen:
            raise ValueError(f"duplicate agent node id {node.id!r}")
        seen.add(node.id)
        if node.service is None and node.source is None and node.input_topic is None:
            raise ValueError(
                f"agent node {node.id!r} has neither an input topic nor a source"
            )
    for gateway in plan.application.gateways:
        for topic_name in _gateway_topics(gateway):
            if topic_name and topic_name not in plan.topics:
                raise ValueError(
                    f"gateway {gateway.id!r} references unknown topic {topic_name!r}"
                )


def _gateway_topics(gateway) -> List[Optional[str]]:
    return [
        gateway.topic,
        gateway.chat_options.get("questions-topic"),
        gateway.chat_options.get("answers-topic"),
    ]
