"""External vector-store datasources over their REST APIs.

Reference: ``langstream-vector-agents/src/main/java/ai/langstream/agents/
vector/{opensearch,pinecone,solr}/`` — the same stores, driven through
their HTTP APIs with aiohttp instead of vendor SDKs (none are bundled in
this image; all three expose full-featured REST surfaces).

Each implements the datasource JSON-spec contract the vector agents use
(``{"action": "search"|"upsert"|"delete", ...}`` with ``?`` params), so
``vector-db-sink`` / ``query-vector-db`` pipelines move between the
native TPU store and these engines by swapping the resource entry only.
Results are normalized to ``{"id", "similarity", **metadata}`` rows.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.agents.datasource import DataSource, _substitute


def _fill(query: str, params: List[Any]) -> Dict[str, Any]:
    return json.loads(_substitute(query, params))


class _RestDataSource(DataSource):
    def __init__(self) -> None:
        self._session = None

    async def _get_session(self, headers: Optional[Dict[str, str]] = None):
        if self._session is None:
            import aiohttp

            auth = self._basic_auth()
            self._session = aiohttp.ClientSession(
                headers=headers or self._headers(), auth=auth
            )
        return self._session

    def _headers(self) -> Dict[str, str]:
        return {}

    def _basic_auth(self):
        return None

    async def _call(self, method: str, url: str, body: Any = None) -> Any:
        session = await self._get_session()
        async with session.request(method, url, json=body) as response:
            text = await response.text()
            if response.status >= 300:
                raise IOError(
                    f"{type(self).__name__} {method} {url}: "
                    f"HTTP {response.status}: {text[:400]}"
                )
            return json.loads(text) if text else {}

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class OpenSearchDataSource(_RestDataSource):
    """OpenSearch/Elasticsearch kNN index (reference:
    ``vector/opensearch/OpenSearchDataSource.java``).

    Config: ``endpoint`` (or ``hosts``), ``index-name``, optional
    ``username``/``password``, ``vector-field`` (default ``embeddings``).
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        super().__init__()
        endpoint = config.get("endpoint") or config.get("hosts")
        if isinstance(endpoint, list):
            endpoint = endpoint[0]
        if not endpoint:
            raise ValueError("opensearch datasource needs 'endpoint'")
        self.endpoint = str(endpoint).rstrip("/")
        self.index = config.get("index-name", config.get("index", "langstream"))
        self.vector_field = config.get("vector-field", "embeddings")
        self.username = config.get("username")
        self.password = config.get("password")

    def _basic_auth(self):
        if self.username:
            import aiohttp

            return aiohttp.BasicAuth(self.username, self.password or "")
        return None

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        if "body" in spec:  # raw passthrough for power users
            body = spec["body"]
        else:
            k = int(spec.get("top-k", 10))
            body = {
                "size": k,
                "query": {
                    "knn": {
                        self.vector_field: {"vector": spec["vector"], "k": k}
                    }
                },
            }
        payload = await self._call(
            "POST", f"{self.endpoint}/{self.index}/_search", body
        )
        out = []
        for hit in payload.get("hits", {}).get("hits", []):
            source = dict(hit.get("_source", {}))
            source.pop(self.vector_field, None)
            out.append({
                "id": hit.get("_id"),
                "similarity": hit.get("_score", 0.0),
                **source,
            })
        return out

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        if action == "upsert":
            document = {
                self.vector_field: spec["vector"],
                **(spec.get("metadata") or {}),
            }
            await self._call(
                "PUT",
                f"{self.endpoint}/{self.index}/_doc/{spec['id']}"
                "?refresh=true",
                document,
            )
            return {"rowcount": 1}
        if action == "delete":
            await self._call(
                "DELETE",
                f"{self.endpoint}/{self.index}/_doc/{spec['id']}"
                "?refresh=true",
            )
            return {"rowcount": 1}
        raise ValueError(f"unsupported opensearch action {action!r}")


class PineconeDataSource(_RestDataSource):
    """Pinecone index over its data-plane REST API (reference:
    ``vector/pinecone/PineconeDataSource.java``).

    Config: ``endpoint`` (index host, e.g. ``https://idx-xxx.svc...``),
    ``api-key``, optional ``namespace``.
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        super().__init__()
        endpoint = config.get("endpoint")
        if not endpoint:
            raise ValueError("pinecone datasource needs 'endpoint'")
        self.endpoint = endpoint.rstrip("/")
        self.api_key = config.get("api-key", "")
        self.namespace = config.get("namespace")

    def _headers(self) -> Dict[str, str]:
        return {"Api-Key": self.api_key}

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        body: Dict[str, Any] = {
            "vector": spec["vector"],
            "topK": int(spec.get("top-k", 10)),
            "includeMetadata": True,
        }
        if self.namespace:
            body["namespace"] = self.namespace
        if spec.get("filter"):
            body["filter"] = spec["filter"]
        payload = await self._call("POST", f"{self.endpoint}/query", body)
        return [
            {
                "id": match.get("id"),
                "similarity": match.get("score", 0.0),
                **(match.get("metadata") or {}),
            }
            for match in payload.get("matches", [])
        ]

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        if action == "upsert":
            body: Dict[str, Any] = {"vectors": [{
                "id": str(spec["id"]),
                "values": spec["vector"],
                "metadata": spec.get("metadata") or {},
            }]}
            if self.namespace:
                body["namespace"] = self.namespace
            payload = await self._call(
                "POST", f"{self.endpoint}/vectors/upsert", body
            )
            return {"rowcount": int(payload.get("upsertedCount", 1))}
        if action == "delete":
            body = {"ids": [str(spec["id"])]}
            if self.namespace:
                body["namespace"] = self.namespace
            await self._call(
                "POST", f"{self.endpoint}/vectors/delete", body
            )
            return {"rowcount": 1}
        raise ValueError(f"unsupported pinecone action {action!r}")


class SolrDataSource(_RestDataSource):
    """Solr collection with dense-vector kNN (reference:
    ``vector/solr/SolrDataSource.java``).

    Config: ``endpoint`` (e.g. ``http://host:8983/solr``),
    ``collection-name``, ``vector-field`` (default ``embeddings``).
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        super().__init__()
        endpoint = config.get("endpoint") or config.get("hosts")
        if not endpoint:
            raise ValueError("solr datasource needs 'endpoint'")
        self.endpoint = str(endpoint).rstrip("/")
        self.collection = config.get(
            "collection-name", config.get("collection", "langstream")
        )
        self.vector_field = config.get("vector-field", "embeddings")

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        k = int(spec.get("top-k", 10))
        vector = "[" + ",".join(str(float(x)) for x in spec["vector"]) + "]"
        body = {
            "query": f"{{!knn f={self.vector_field} topK={k}}}{vector}",
            "limit": k,
            "fields": "*,score",
        }
        payload = await self._call(
            "POST", f"{self.endpoint}/{self.collection}/select", body
        )
        out = []
        for doc in payload.get("response", {}).get("docs", []):
            doc = dict(doc)
            doc.pop(self.vector_field, None)
            out.append({
                "id": doc.pop("id", None),
                "similarity": doc.pop("score", 0.0),
                **doc,
            })
        return out

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        if action == "upsert":
            document = {
                "id": str(spec["id"]),
                self.vector_field: spec["vector"],
                **(spec.get("metadata") or {}),
            }
            await self._call(
                "POST",
                f"{self.endpoint}/{self.collection}/update?commit=true",
                [document],
            )
            return {"rowcount": 1}
        if action == "delete":
            await self._call(
                "POST",
                f"{self.endpoint}/{self.collection}/update?commit=true",
                {"delete": [str(spec["id"])]},
            )
            return {"rowcount": 1}
        raise ValueError(f"unsupported solr action {action!r}")


class AstraDataSource(_RestDataSource):
    """Astra DB via the Data API (JSON over HTTP — reference:
    ``vector/astra/``; the Java driver's CQL path is replaced by Astra's
    own document/vector REST surface, no driver needed).

    Config: ``endpoint`` (the database API endpoint), ``token``
    (``AstraCS:...``), ``keyspace`` (default ``default_keyspace``),
    ``collection-name``.
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        super().__init__()
        endpoint = config.get("endpoint") or config.get("api-endpoint")
        if not endpoint:
            raise ValueError("astra datasource needs 'endpoint'")
        self.endpoint = endpoint.rstrip("/")
        self.token = config.get("token", "")
        self.keyspace = config.get("keyspace", "default_keyspace")
        self.collection = config.get(
            "collection-name", config.get("collection", "langstream")
        )

    def _headers(self) -> Dict[str, str]:
        return {"Token": self.token}

    async def _command(self, body: Dict[str, Any]) -> Dict[str, Any]:
        url = (
            f"{self.endpoint}/api/json/v1/{self.keyspace}/{self.collection}"
        )
        return await self._call("POST", url, body)

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        if "find" in spec:  # raw passthrough
            payload = await self._command({"find": spec["find"]})
        else:
            find: Dict[str, Any] = {
                "sort": {"$vector": spec["vector"]},
                "options": {
                    "limit": int(spec.get("top-k", 10)),
                    "includeSimilarity": True,
                },
            }
            if spec.get("filter"):
                find["filter"] = spec["filter"]
            payload = await self._command({"find": find})
        out = []
        for document in (
            payload.get("data", {}).get("documents", []) or []
        ):
            document = dict(document)
            document.pop("$vector", None)
            out.append({
                "id": document.pop("_id", None),
                "similarity": document.pop("$similarity", 0.0),
                **document,
            })
        return out

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        if action == "upsert":
            document = {
                "_id": str(spec["id"]),
                "$vector": spec["vector"],
                **(spec.get("metadata") or {}),
            }
            # findOneAndReplace with upsert = true: idempotent writes
            await self._command({
                "findOneAndReplace": {
                    "filter": {"_id": str(spec["id"])},
                    "replacement": document,
                    "options": {"upsert": True},
                }
            })
            return {"rowcount": 1}
        if action == "delete":
            payload = await self._command({
                "deleteOne": {"filter": {"_id": str(spec["id"])}}
            })
            return {
                "rowcount": int(
                    payload.get("status", {}).get("deletedCount", 0)
                )
            }
        raise ValueError(f"unsupported astra action {action!r}")


class MilvusDataSource(_RestDataSource):
    """Milvus / Zilliz over the v2 REST API (reference:
    ``vector/milvus/MilvusDataSource.java:100-160``, which drives the
    Java SDK's high-level ``SearchSimpleParam``; config keys mirror
    ``MilvusDatasourceConfig.java``: ``url`` (Zilliz) OR ``host``+
    ``port``, and ``token`` OR ``user``/``password`` — Milvus's REST
    auth accepts ``user:password`` as a bearer token).

    Query spec follows SearchSimpleParam's JSON spelling
    (``collection-name``/``collectionName``, ``vectors``, ``limit`` or
    ``top-k``, ``output-fields``, ``filter``, ``offset``); the sink's
    generic ``{"action": "upsert"|"delete", id, vector, metadata}``
    statements map onto ``/v2/vectordb/entities/{upsert,delete}``.
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        super().__init__()
        url = config.get("url")
        if not url:
            host = config.get("host", "localhost")
            port = int(config.get("port", 19530))
            url = f"http://{host}:{port}"
        self.base = str(url).rstrip("/")
        token = config.get("token")
        if not token and config.get("user"):
            token = f"{config['user']}:{config.get('password', '')}"
        self.token = token
        self.collection = (
            config.get("collection-name") or config.get("collection")
        )
        self.vector_field = config.get("vector-field", "vector")

    def _headers(self) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    async def _v2(
        self, op: str, body: Dict[str, Any], group: str = "entities"
    ) -> Dict[str, Any]:
        """POST a v2 REST command (``/v2/vectordb/{group}/{op}``) and
        enforce Milvus's body-level error-code convention (HTTP 200
        with a non-zero ``code`` on failure). Asset managers reuse this
        with ``group="collections"``."""
        payload = await self._call(
            "POST", f"{self.base}/v2/vectordb/{group}/{op}", body
        )
        code = payload.get("code", 0)
        if code not in (0, 200):
            raise IOError(
                f"milvus {group}/{op}: code {code}: {payload.get('message')}"
            )
        return payload

    def _collection(self, spec: Dict[str, Any]) -> str:
        collection = (
            spec.get("collection-name")
            or spec.get("collectionName")
            or self.collection
        )
        if not collection:
            raise ValueError(
                "milvus spec needs 'collection-name' (or set it on the "
                "datasource resource)"
            )
        return collection

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        vector = (
            spec.get("vectors") or spec.get("vector") or spec.get("data")
        )
        if vector and not isinstance(vector[0], list):
            vector = [vector]
        body: Dict[str, Any] = {
            "collectionName": self._collection(spec),
            "data": vector,
            "limit": int(spec.get("top-k", spec.get("limit", 10))),
            "annsField": spec.get("anns-field", self.vector_field),
        }
        fields = spec.get("output-fields") or spec.get("outputFields")
        body["outputFields"] = fields or ["*"]
        for key in ("filter", "offset"):
            if spec.get(key):
                body[key] = spec[key]
        rows = (await self._v2("search", body)).get("data") or []
        out = []
        for row in rows:
            row = dict(row)
            row.pop(self.vector_field, None)
            out.append({
                "id": row.pop("id", None),
                "similarity": row.pop("distance", 0.0),
                **row,
            })
        return out

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        collection = self._collection(spec)
        if action == "upsert":
            entity = {
                "id": spec["id"],
                self.vector_field: spec["vector"],
                **(spec.get("metadata") or {}),
            }
            payload = await self._v2(
                "upsert", {"collectionName": collection, "data": [entity]}
            )
            count = (payload.get("data") or {}).get("upsertCount", 1)
            return {"rowcount": int(count)}
        if action == "delete":
            fltr = spec.get("filter")
            if not fltr:
                if spec.get("id") is None:
                    raise ValueError(
                        "milvus delete needs 'id' or 'filter'"
                    )
                fltr = f'id in [{json.dumps(spec["id"])}]'
            await self._v2(
                "delete", {"collectionName": collection, "filter": fltr}
            )
            return {"rowcount": 1}
        raise ValueError(f"unsupported milvus action {action!r}")
