"""The expression language for predicates, computed fields, and routing.

Equivalent of the reference's JSTL EL layer
(``langstream-agents/langstream-agents-commons/src/main/java/ai/langstream/ai/agents/commons/jstl/JstlEvaluator.java:29``,
``JstlFunctions.java:44``, ``JstlPredicate``): agents evaluate expressions
like ``value.question`` or ``fn:lowercase(value.name)`` against a record
context exposing ``key``, ``value``, ``properties`` (headers), ``origin``,
``timestamp``.

TPU-rebuild deviation (documented API difference): expressions use **Python
expression syntax**, safely sandboxed via an AST whitelist — no imports, no
calls except into the ``fn`` namespace and whitelisted methods, no
attribute access to dunder names. JSTL's ``fn:name(...)`` spelling is
accepted and rewritten to ``fn.name(...)`` for compatibility with ported
pipelines.
"""

from __future__ import annotations

import ast
import datetime
import json
import re
import time
import uuid
from typing import Any, Dict, List, Optional


class ExpressionError(ValueError):
    pass


class _AttrDict(dict):
    """Dict with attribute-style access so ``value.question`` works.

    Data wins over dict methods: ``value.items`` returns the ``items``
    *field* when present (common JSON name), not the bound method.
    Missing fields read as None.
    """

    def __getattribute__(self, name: str) -> Any:
        if not name.startswith("__") and dict.__contains__(self, name):
            return _wrap(dict.__getitem__(self, name))
        return object.__getattribute__(self, name)

    def __getattr__(self, name: str) -> Any:
        return None


def _wrap(value: Any) -> Any:
    if isinstance(value, _AttrDict):
        return value
    if isinstance(value, dict):
        return _AttrDict(value)
    return value


class Functions:
    """The ``fn`` namespace (``JstlFunctions.java:44``)."""

    @staticmethod
    def uppercase(value: Any) -> Optional[str]:
        return None if value is None else str(value).upper()

    @staticmethod
    def lowercase(value: Any) -> Optional[str]:
        return None if value is None else str(value).lower()

    @staticmethod
    def trim(value: Any) -> Optional[str]:
        return None if value is None else str(value).strip()

    @staticmethod
    def concat(*parts: Any) -> str:
        return "".join("" if p is None else str(p) for p in parts)

    @staticmethod
    def concat3(a: Any, b: Any, c: Any) -> str:
        return Functions.concat(a, b, c)

    @staticmethod
    def contains(haystack: Any, needle: Any) -> bool:
        if haystack is None or needle is None:
            return False
        return str(needle) in str(haystack)

    @staticmethod
    def coalesce(value: Any, fallback: Any) -> Any:
        return fallback if value is None else value

    @staticmethod
    def split(value: Any, separator: str) -> List[str]:
        if value is None:
            return []
        return str(value).split(separator)

    @staticmethod
    def replace(value: Any, pattern: str, replacement: str) -> Optional[str]:
        return None if value is None else re.sub(pattern, replacement, str(value))

    @staticmethod
    def str(value: Any) -> Optional[str]:  # noqa: A003 — JSTL name
        # class attributes are not in method scope, so `str` here is builtin
        return None if value is None else str(value)

    @staticmethod
    def toDouble(value: Any) -> Optional[float]:
        return None if value is None else float(value)

    @staticmethod
    def toInt(value: Any) -> Optional[int]:
        return None if value is None else int(float(value))

    @staticmethod
    def toJson(value: Any) -> str:
        return json.dumps(value, ensure_ascii=False, default=str)

    @staticmethod
    def fromJson(value: Any) -> Any:
        return None if value is None else json.loads(value)

    @staticmethod
    def len(value: Any) -> int:  # noqa: A003
        return 0 if value is None else len(value)

    @staticmethod
    def now() -> int:
        return int(time.time() * 1000)

    @staticmethod
    def uuid() -> str:
        return uuid.uuid4().hex

    @staticmethod
    def timestampAdd(timestamp: Any, delta: Any, unit: str) -> int:
        base = int(timestamp)
        amount = int(delta)
        scale = {
            "years": 31536000000,
            "months": 2592000000,
            "days": 86400000,
            "hours": 3600000,
            "minutes": 60000,
            "seconds": 1000,
            "millis": 1,
        }.get(unit)
        if scale is None:
            raise ExpressionError(f"unknown time unit {unit!r}")
        return base + amount * scale

    @staticmethod
    def dateadd(value: Any, delta: Any, unit: str) -> int:
        if isinstance(value, str):
            parsed = datetime.datetime.fromisoformat(value.replace("Z", "+00:00"))
            value = int(parsed.timestamp() * 1000)
        return Functions.timestampAdd(value, delta, unit)

    @staticmethod
    def emptyString() -> str:
        return ""

    @staticmethod
    def emptyList() -> list:
        return []

    @staticmethod
    def emptyMap() -> dict:
        return {}

    @staticmethod
    def listAdd(lst: Any, item: Any) -> list:
        out = list(lst or [])
        out.append(item)
        return out

    @staticmethod
    def listOf(*items: Any) -> list:
        return list(items)

    @staticmethod
    def mapOf(*kv: Any) -> dict:
        if len(kv) % 2:
            raise ExpressionError("mapOf requires an even number of arguments")
        return {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}

    @staticmethod
    def mapPut(mapping: Any, key: Any, value: Any) -> dict:
        out = dict(mapping or {})
        out[key] = value
        return out

    @staticmethod
    def mapRemove(mapping: Any, key: Any) -> dict:
        out = dict(mapping or {})
        out.pop(key, None)
        return out


_ALLOWED_NODES = (
    ast.Expression,
    ast.BoolOp, ast.And, ast.Or,
    ast.UnaryOp, ast.Not, ast.USub, ast.UAdd,
    ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.FloorDiv,
    ast.Pow,
    ast.Compare, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn, ast.Is, ast.IsNot,
    ast.IfExp,
    ast.Call, ast.keyword,
    ast.Attribute, ast.Subscript, ast.Index, ast.Slice,
    ast.Name, ast.Load,
    ast.Constant,
    ast.List, ast.Tuple, ast.Dict, ast.Set,
)

_JSTL_FN = re.compile(r"\bfn:([a-zA-Z_][a-zA-Z0-9_]*)")


def _validate(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ExpressionError(
                f"disallowed syntax in expression: {type(node).__name__}"
            )
        if isinstance(node, ast.Attribute) and node.attr.startswith("__"):
            raise ExpressionError("dunder attribute access is not allowed")
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise ExpressionError("dunder names are not allowed")
        if isinstance(node, ast.Call):
            func = node.func
            ok = (
                isinstance(func, ast.Attribute)
                or (isinstance(func, ast.Name) and func.id in _SAFE_CALLS)
            )
            if not ok:
                raise ExpressionError("only fn.* and method calls are allowed")


_SAFE_CALLS = {"len", "str", "int", "float", "bool", "min", "max", "abs", "round", "sorted", "sum"}

_SAFE_GLOBALS = {
    "len": len, "str": str, "int": int, "float": float, "bool": bool,
    "min": min, "max": max, "abs": abs, "round": round, "sorted": sorted,
    "sum": sum, "true": True, "false": False, "null": None, "None": None,
    "True": True, "False": False,
}


class Expression:
    """A compiled, sandboxed expression."""

    def __init__(self, source: str) -> None:
        self.source = source
        normalized = _JSTL_FN.sub(r"fn.\1", source)
        # JSTL wrapping `${...}` is accepted and stripped
        stripped = normalized.strip()
        if stripped.startswith("${") and stripped.endswith("}"):
            stripped = stripped[2:-1]
        try:
            tree = ast.parse(stripped, mode="eval")
        except SyntaxError as error:
            raise ExpressionError(f"bad expression {source!r}: {error}") from error
        _validate(tree)
        self._code = compile(tree, filename="<expression>", mode="eval")

    def evaluate(self, context: Dict[str, Any]) -> Any:
        scope = dict(_SAFE_GLOBALS)
        scope["fn"] = Functions
        for key, value in context.items():
            scope[key] = _wrap(value)
        try:
            return eval(self._code, {"__builtins__": {}}, scope)  # noqa: S307
        except ExpressionError:
            raise
        except Exception as error:  # noqa: BLE001
            raise ExpressionError(
                f"error evaluating {self.source!r}: {error}"
            ) from error


def evaluate(source: str, context: Dict[str, Any]) -> Any:
    return Expression(source).evaluate(context)


def evaluate_predicate(source: str, context: Dict[str, Any]) -> bool:
    return bool(Expression(source).evaluate(context))


# ---------------------------------------------------------------------- #
# Mustache-style prompt templating ({{ value.question }})
# ---------------------------------------------------------------------- #
_MUSTACHE = re.compile(r"\{\{\{?\s*([^}]+?)\s*\}?\}\}")


def render_template(template: str, context: Dict[str, Any]) -> str:
    """Render ``{{ path.or.expression }}`` placeholders (the prompt
    templating of ``ChatCompletionsStep``; the reference uses Mustache)."""

    def sub(match: "re.Match[str]") -> str:
        expression = match.group(1)
        value = Expression(expression).evaluate(context)
        if value is None:
            return ""
        if isinstance(value, (dict, list)):
            return json.dumps(value, ensure_ascii=False, default=str)
        return str(value)

    return _MUSTACHE.sub(sub, template)
