"""FLARE controller: iterative active-retrieval loop.

Equivalent of the reference's ``flare-controller`` agent
(langstream-agents/langstream-ai-agents/src/main/java/ai/langstream/ai/agents/flare/FlareControllerAgent.java:42):
after a text completion that returned per-token log-probabilities, scan
for *low-confidence spans* (tokens whose probability falls below
``min-prob``), merge nearby spans (``min-token-gap``) with padding
(``num-pad-tokens``), and:

- no spans → the answer is confident: pass the record through;
- spans found → write them to ``retrieve-documents-field`` and send the
  record to ``loop-topic`` (incrementing ``num-iterations-field``), so
  the pipeline's retrieval stage fetches more context about exactly the
  uncertain parts and re-generates. ``max-iterations`` bounds the loop.

The TPU angle: the jax-local engine produces real token logprobs from
its own decode loop (no external API needed), making FLARE loops free of
per-iteration network round-trips.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.agents.transform import TransformContext

logger = logging.getLogger(__name__)

_WORD = re.compile(r"\w")


def low_confidence_spans(
    tokens: List[str],
    logprobs: List[float],
    *,
    min_prob: float = 0.2,
    min_token_gap: int = 5,
    num_pad_tokens: int = 2,
) -> List[str]:
    """Spans of consecutive low-confidence word tokens, merged when
    closer than ``min_token_gap`` and padded by ``num_pad_tokens``
    (reference: ``FlareControllerAgent.lowConfidenceSpans``)."""
    low_idx = [
        i
        for i in range(min(len(tokens), len(logprobs)))
        if math.exp(logprobs[i]) < min_prob and _WORD.search(tokens[i] or "")
    ]
    if not low_idx:
        return []
    spans = [[low_idx[0], low_idx[0] + num_pad_tokens + 1]]
    for prev, idx in zip(low_idx, low_idx[1:]):
        end = idx + num_pad_tokens + 1
        if idx - prev < min_token_gap:
            spans[-1][1] = end
        else:
            spans.append([idx, end])
    return [
        "".join(tokens[start:min(end, len(tokens))])
        for start, end in spans
    ]


class FlareControllerAgent(SingleRecordProcessor):
    """``flare-controller`` agent."""

    agent_type = "flare-controller"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.tokens_field = configuration.get("tokens-field", "value.tokens")
        self.logprobs_field = configuration.get(
            "logprobs-field", "value.logprobs"
        )
        self.loop_topic = configuration["loop-topic"]
        self.retrieve_field = configuration.get(
            "retrieve-documents-field", "value.documents_to_retrieve"
        )
        self.min_prob = float(configuration.get("min-prob", 0.2))
        self.min_token_gap = int(configuration.get("min-token-gap", 5))
        self.num_pad_tokens = int(configuration.get("num-pad-tokens", 2))
        self.max_iterations = int(configuration.get("max-iterations", 10))
        self.iterations_field = configuration.get(
            "num-iterations-field", "value.flare_iterations"
        )
        self._producer = None

    async def close(self) -> None:
        if self._producer is not None:
            await self._producer.close()
            self._producer = None

    async def _loop_producer(self):
        if self._producer is None:
            producer = self.context.topic_connections.create_producer(
                self.agent_id, {"topic": self.loop_topic}
            )
            await producer.start()
            self._producer = producer
        return self._producer

    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        iterations = ctx.get_field(self.iterations_field) or 0
        if int(iterations) >= self.max_iterations:
            logger.info(
                "flare: record hit max iterations (%s), passing through",
                iterations,
            )
            return [record]
        tokens = ctx.get_field(self.tokens_field) or []
        logprobs = ctx.get_field(self.logprobs_field) or []
        spans = low_confidence_spans(
            list(tokens), [float(p) for p in logprobs],
            min_prob=self.min_prob,
            min_token_gap=self.min_token_gap,
            num_pad_tokens=self.num_pad_tokens,
        )
        if not spans:
            return [record]
        ctx.set_field(self.retrieve_field, spans)
        ctx.set_field(self.iterations_field, int(iterations) + 1)
        producer = await self._loop_producer()
        await producer.write(ctx.to_record())
        logger.info(
            "flare: %d low-confidence spans -> %s", len(spans), self.loop_topic
        )
        return []  # control passed to the loop topic
