"""HTTP request agent: call an HTTP service per record.

Equivalent of the reference's ``http-request`` processor
(``langstream-agents/langstream-agent-http-request/.../HttpRequestAgent.java:51``):
url/method/headers/query templates evaluated against the record, response
body lands in ``output-field`` (JSON-parsed when the response is JSON).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.agents.el import render_template
from langstream_tpu.agents.transform import TransformContext


class HttpRequestAgent(SingleRecordProcessor):
    agent_type = "http-request"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.url = configuration["url"]
        self.method = configuration.get("method", "GET").upper()
        self.output_field = configuration.get("output-field", "value")
        self.headers = configuration.get("headers", {}) or {}
        self.query_string = configuration.get("query-string", {}) or {}
        self.body = configuration.get("body")
        self.allow_redirects = bool(configuration.get("allow-redirects", True))
        self.handle_cookies = bool(configuration.get("handle-cookies", True))
        self._session = None

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            cookie_jar=aiohttp.CookieJar()
            if self.handle_cookies
            else aiohttp.DummyCookieJar()
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        el_ctx = ctx.el_context()
        url = render_template(self.url, el_ctx)
        params = {
            key: render_template(str(value), el_ctx)
            for key, value in self.query_string.items()
        }
        headers = {
            key: render_template(str(value), el_ctx)
            for key, value in self.headers.items()
        }
        body = render_template(self.body, el_ctx) if self.body else None
        async with self._session.request(
            self.method,
            url,
            params=params,
            headers=headers,
            data=body,
            allow_redirects=self.allow_redirects,
        ) as response:
            response.raise_for_status()
            content_type = response.headers.get("Content-Type", "")
            if "json" in content_type:
                payload: Any = await response.json()
            else:
                payload = await response.text()
        ctx.set_field(self.output_field, payload)
        return [ctx.to_record()]
