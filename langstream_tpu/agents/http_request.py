"""HTTP request agent: call an HTTP service per record.

Equivalent of the reference's ``http-request`` processor
(``langstream-agents/langstream-agent-http-request/.../HttpRequestAgent.java:51``):
url/method/headers/query templates evaluated against the record, response
body lands in ``output-field`` (JSON-parsed when the response is JSON).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.agents.el import render_template
from langstream_tpu.agents.transform import TransformContext


class HttpRequestAgent(SingleRecordProcessor):
    agent_type = "http-request"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.url = configuration["url"]
        self.method = configuration.get("method", "GET").upper()
        self.output_field = configuration.get("output-field", "value")
        self.headers = configuration.get("headers", {}) or {}
        self.query_string = configuration.get("query-string", {}) or {}
        self.body = configuration.get("body")
        self.allow_redirects = bool(configuration.get("allow-redirects", True))
        self.handle_cookies = bool(configuration.get("handle-cookies", True))
        self._session = None

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            cookie_jar=aiohttp.CookieJar()
            if self.handle_cookies
            else aiohttp.DummyCookieJar()
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        el_ctx = ctx.el_context()
        url = render_template(self.url, el_ctx)
        params = {
            key: render_template(str(value), el_ctx)
            for key, value in self.query_string.items()
        }
        headers = {
            key: render_template(str(value), el_ctx)
            for key, value in self.headers.items()
        }
        body = render_template(self.body, el_ctx) if self.body else None
        async with self._session.request(
            self.method,
            url,
            params=params,
            headers=headers,
            data=body,
            allow_redirects=self.allow_redirects,
        ) as response:
            response.raise_for_status()
            content_type = response.headers.get("Content-Type", "")
            if "json" in content_type:
                payload: Any = await response.json()
            else:
                payload = await response.text()
        ctx.set_field(self.output_field, payload)
        return [ctx.to_record()]


class LangServeInvokeAgent(SingleRecordProcessor):
    """``langserve-invoke``: call a LangChain LangServe runnable.

    Equivalent of the reference's LangServe client
    (``langstream-agents/langstream-agent-http-request/.../LangServeInvokeAgent.java:49``):
    POST ``{"input": {fields...}}`` to the service URL; an ``/invoke``
    endpoint's ``output`` lands in ``output-field``, a ``/stream``
    endpoint's SSE chunks are forwarded to ``stream-to-topic`` as they
    arrive (content in ``content-field``) and concatenated into the
    final output.
    """

    agent_type = "langserve-invoke"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.url = configuration["url"]
        self.fields = [
            (f["name"], f["expression"])
            for f in configuration.get("fields", []) or []
        ]
        self.output_field = configuration.get("output-field", "value")
        self.content_field = configuration.get("content-field", "value")
        self.stream_to_topic = configuration.get("stream-to-topic")
        self.min_chunks = int(configuration.get("min-chunks-per-message", 20))
        self.headers = configuration.get("headers", {}) or {}
        self._session = None
        self._producer = None

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession()

    async def close(self) -> None:
        if self._producer is not None:
            await self._producer.close()
            self._producer = None
        if self._session is not None:
            await self._session.close()

    async def _stream_producer(self):
        if self._producer is None:
            producer = self.context.topic_connections.create_producer(
                self.agent_id, {"topic": self.stream_to_topic}
            )
            await producer.start()
            self._producer = producer
        return self._producer

    @staticmethod
    def _chunk_text(payload: Any) -> str:
        if isinstance(payload, str):
            return payload
        if isinstance(payload, dict):
            return str(payload.get("content", payload.get("output", "")))
        return str(payload)

    async def process_record(self, record: Record) -> List[Record]:
        from langstream_tpu.agents.el import Expression

        ctx = TransformContext(record)
        el_ctx = ctx.el_context()
        payload = {
            "input": {
                name: Expression(expression).evaluate(el_ctx)
                for name, expression in self.fields
            }
        }
        streaming = self.url.rstrip("/").endswith("/stream")
        async with self._session.post(
            self.url, json=payload, headers=self.headers
        ) as response:
            response.raise_for_status()
            if not streaming:
                body = await response.json()
                output = body.get("output", body) if isinstance(body, dict) else body
                ctx.set_field(self.output_field, output)
                return [ctx.to_record()]
            # SSE: forward "data" events to the stream topic with the
            # reference's exponential chunk batching (1, 2, 4, ... up to
            # min-chunks-per-message chunks per emitted record)
            parts: List[str] = []
            buffer: List[str] = []
            batch_size, index = 1, 0
            producer = (
                await self._stream_producer() if self.stream_to_topic else None
            )

            async def flush(last: bool) -> None:
                nonlocal index, batch_size, buffer
                if producer is None or (not buffer and not last):
                    return
                # deep-copy per chunk: set_field mutates the value dict
                # in place, and every chunk record must not alias it
                import copy as _copymod

                chunk_ctx = TransformContext(record)
                chunk_ctx.value = _copymod.deepcopy(chunk_ctx.value)
                chunk_ctx.key = _copymod.deepcopy(chunk_ctx.key)
                chunk_ctx.set_field(self.content_field, "".join(buffer))
                chunk_ctx.properties["stream-index"] = str(index)
                chunk_ctx.properties["stream-last-message"] = str(last).lower()
                await producer.write(chunk_ctx.to_record())
                index += 1
                batch_size = min(batch_size * 2, max(self.min_chunks, 1))
                buffer = []

            async for raw_line in response.content:
                line = raw_line.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                data = line[len("data:"):].strip()
                if data in ("", "[DONE]"):
                    continue
                try:
                    parsed: Any = json.loads(data)
                except ValueError:
                    parsed = data
                text = self._chunk_text(parsed)
                if not text:
                    continue
                parts.append(text)
                buffer.append(text)
                if len(buffer) >= batch_size:
                    await flush(last=False)
            await flush(last=True)
        ctx.set_field(self.output_field, "".join(parts))
        return [ctx.to_record()]
