"""Built-in agent library — the "ops" of the framework.

Equivalent of the reference's ``langstream-agents/*`` modules. Importing
this package registers every built-in agent type with the runtime registry
(the reference uses ServiceLoader NAR scanning;
``langstream-api/.../runner/code/AgentCodeRegistry.java:32``). Registration
is lazy — the implementing module loads on first instantiation, keeping
import of the core cheap.
"""

from __future__ import annotations

import importlib

from langstream_tpu.runtime.registry import register_agent


def _lazy(module_name: str, class_name: str):
    def factory():
        module = importlib.import_module(module_name)
        return getattr(module, class_name)()

    return factory


# type → implementation, mirroring the reference's agent-type tables
# (flow map: flow/FlowControlAgentsCodeProvider.java:26-34; GenAI step types:
# GenAIToolKitFunctionAgentProvider.java:53-74; text agents: §2.4 SURVEY.md)
_BUILTIN = {
    "identity": ("langstream_tpu.runtime.runner", "IdentityProcessor"),
    "composite-agent": ("langstream_tpu.runtime.composite", "CompositeAgentProcessor"),
    # in-process python agents (the reference runs these over localhost gRPC:
    # langstream-agent-grpc/.../PythonGrpcServer.java:31)
    "python-processor": ("langstream_tpu.agents.python_agents", "PythonProcessorAgent"),
    "python-source": ("langstream_tpu.agents.python_agents", "PythonSourceAgent"),
    "python-sink": ("langstream_tpu.agents.python_agents", "PythonSinkAgent"),
    "python-service": ("langstream_tpu.agents.python_agents", "PythonServiceAgent"),
    # the GenAI toolkit executor (all declarative steps run through it)
    "ai-tools": ("langstream_tpu.agents.genai", "GenAIToolKitAgent"),
    # text processing
    "text-splitter": ("langstream_tpu.agents.text", "TextSplitterAgent"),
    "document-to-json": ("langstream_tpu.agents.text", "DocumentToJsonAgent"),
    "text-normaliser": ("langstream_tpu.agents.text", "TextNormaliserAgent"),
    "language-detector": ("langstream_tpu.agents.text", "LanguageDetectorAgent"),
    "text-extractor": ("langstream_tpu.agents.text", "TextExtractorAgent"),
    # flow control
    "dispatch": ("langstream_tpu.agents.flow", "DispatchAgent"),
    "timer-source": ("langstream_tpu.agents.flow", "TimerSourceAgent"),
    "trigger-event": ("langstream_tpu.agents.flow", "TriggerEventAgent"),
    "log-event": ("langstream_tpu.agents.flow", "LogEventAgent"),
    # vector / RAG
    "vector-db-sink": ("langstream_tpu.agents.vector", "VectorDBSinkAgent"),
    "query-vector-db": ("langstream_tpu.agents.vector", "QueryVectorDBAgent"),
    "re-rank": ("langstream_tpu.agents.rerank", "ReRankAgent"),
    # sources / connectors
    "webcrawler-source": ("langstream_tpu.agents.webcrawler", "WebCrawlerSource"),
    "s3-source": ("langstream_tpu.agents.storage", "S3Source"),
    "file-source": ("langstream_tpu.agents.storage", "FileSource"),
    "azure-blob-storage-source": ("langstream_tpu.agents.storage", "AzureBlobStorageSource"),
    "http-request": ("langstream_tpu.agents.http_request", "HttpRequestAgent"),
    "langserve-invoke": ("langstream_tpu.agents.http_request", "LangServeInvokeAgent"),
    # iterative retrieval control
    "flare-controller": ("langstream_tpu.agents.flare", "FlareControllerAgent"),
    # generic connector escape hatch (reference role: Camel / Kafka Connect)
    "exec-source": ("langstream_tpu.agents.connector", "ExecSource"),
    "camel-source": ("langstream_tpu.agents.camel", "CamelSourceAgent"),
    "exec-sink": ("langstream_tpu.agents.connector", "ExecSink"),
    # Kafka Connect adapters (connector managed via the Connect REST
    # API; data rides the kafka topic runtime)
    "kafka-connect-source": (
        "langstream_tpu.agents.kafka_connect", "KafkaConnectSourceAgent"
    ),
    "kafka-connect-sink": (
        "langstream_tpu.agents.kafka_connect", "KafkaConnectSinkAgent"
    ),
}


for _type, (_module, _cls) in _BUILTIN.items():
    register_agent(_type, _lazy(_module, _cls))
