"""``camel-source``: Apache Camel endpoint URIs mapped onto native
sources.

Reference: ``langstream-agent-camel/src/main/java/ai/langstream/agents/
camel/CamelSource.java:171-232`` — a generic connector escape hatch that
consumes any Camel ``component-uri`` and turns exchanges into records
(body → value, exchange headers → headers, ``key-header`` names the
header used as the record key).

The TPU build has no JVM, so the full Camel component zoo cannot run
in-process. Instead the URI is dispatched through a **scheme registry**
(:data:`CAMEL_SCHEMES`, extensible via :func:`register_camel_scheme` —
plugin packages can map additional component families) and the COMMON
component URIs are executed natively by delegating to the framework's
own sources, keeping pipeline definitions portable as-is:

- ``timer:name?period=1000&repeatCount=N`` — periodic records with
  Camel's ``timer``/``firedTime`` headers;
- ``file:/dir?delete=true&fileExtensions=txt`` — directory source
  (delegates to :class:`agents.storage.FileSource`);
- ``http://…`` / ``https://…?delay=500`` — polling HTTP consumer;
- ``kafka:topic?brokers=host:port&groupId=g`` — consumes a Kafka topic
  through the framework's own wire-protocol client (Camel's kafka
  component options ``brokers``/``groupId``/``autoOffsetReset``);
- ``netty-http:http://bind:port/path`` — embedded HTTP *server*
  consumer (Camel's netty-http in ``from()`` position listens): every
  incoming request becomes a record;
- ``aws2-s3://bucket?accessKey=…&deleteAfterRead=false`` — S3 object
  polling via the native SigV4 client (``agents/storage.S3Source``);
- ``azure-storage-blob://account/container?accessKey=…`` — Azure blob
  polling via the native REST client;
- ``pulsar:persistent://tenant/ns/topic?webServiceUrl=…`` — Pulsar
  consumer via the framework's WebSocket runtime (``topics/pulsar``) —
  the messaging analogue; the binary ``serviceUrl`` protocol errors
  with guidance.

Unsupported schemes fail **at plan time** (planner calls
:func:`validate_component_uri`) with the supported list and the
exec-source bridge recipe.

Anything else raises with the honest escape hatch: register a scheme
mapping from a plugin, or run the real Camel route in its own process
via ``exec-source`` (``agents/connector.py``). ``component-options``
merge into the URI's query parameters, matching Camel's own config
layering.
"""

from __future__ import annotations

import asyncio
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Tuple

from langstream_tpu.api.agent import AgentSource
from langstream_tpu.api.records import Record, now_millis


def parse_component_uri(
    uri: str, options: Optional[Dict[str, Any]] = None
) -> Tuple[str, str, List[Tuple[str, str]]]:
    """Split a Camel endpoint URI into (scheme, path, param pairs).
    Pairs preserve duplicates and valueless flags (``?delete`` keeps a
    blank value); query parameters and ``component-options`` merge,
    options appended last — Camel's own layering."""
    scheme, _, rest = uri.partition(":")
    if not scheme or not rest:
        raise ValueError(f"not a Camel endpoint URI: {uri!r}")
    path, _, query = rest.partition("?")
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    for key, value in (options or {}).items():
        pairs.append((str(key), str(value)))
    scheme = scheme.lower()
    if scheme in ("timer", "kafka"):
        path = path.strip("/")
    return scheme, path, pairs


def _last(pairs: List[Tuple[str, str]], key: str, default: str) -> str:
    value = default
    for name, item in pairs:
        if name == key:
            value = item
    return value


def _flag(pairs: List[Tuple[str, str]], key: str) -> bool:
    """Boolean endpoint option: ``=true`` or a valueless ``?flag``."""
    value = _last(pairs, key, "false")
    return value == "" or value.lower() == "true"


_DURATION_UNITS = {"ms": 1.0, "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0}


def _duration_ms(value: str, key: str) -> float:
    """Camel duration syntax: plain milliseconds or a single-unit
    suffix (``5s``, ``1m``, ``250ms``)."""
    text = str(value).strip()
    for suffix in ("ms", "s", "m", "h"):
        if text.endswith(suffix):
            number = text[: -len(suffix)]
            try:
                return float(number) * _DURATION_UNITS[suffix]
            except ValueError:
                break
    try:
        return float(text)
    except ValueError:
        raise ValueError(
            f"camel-source: cannot parse {key}={value!r} (use "
            "milliseconds or a single-unit duration like 5s, 1m, 250ms)"
        ) from None


# ------------------------------------------------------------------ #
# per-scheme endpoints — each is a normal AgentSource the facade
# delegates to, so read/commit/close flow uniformly
# ------------------------------------------------------------------ #


class _TimerEndpoint(AgentSource):
    def __init__(self, path: str, pairs: List[Tuple[str, str]]) -> None:
        self.timer_name = path
        self.period = _duration_ms(_last(pairs, "period", "1000"), "period") / 1000.0
        repeat = int(_last(pairs, "repeatCount", "0"))
        self.remaining: Optional[int] = repeat if repeat > 0 else None
        self._next_fire = time.monotonic() + self.period

    async def read(self, max_records: int = 100) -> List[Record]:
        if self.remaining is not None and self.remaining <= 0:
            # exhausted: yield so the runner's poll loop never busy-spins
            await asyncio.sleep(0.2)
            return []
        delay = self._next_fire - time.monotonic()
        if delay > 0:
            # bounded sleep (not the full delay) so close() stays prompt
            await asyncio.sleep(min(delay, 0.2))
            if time.monotonic() < self._next_fire:
                return []
        self._next_fire = time.monotonic() + self.period
        if self.remaining is not None:
            self.remaining -= 1
        headers = (("timer", self.timer_name), ("firedTime", now_millis()))
        return [Record(value=None, headers=headers, timestamp=now_millis())]

    async def commit(self, records: List[Record]) -> None:
        pass


class _HttpPollEndpoint(AgentSource):
    def __init__(
        self, uri: str, pairs: List[Tuple[str, str]]
    ) -> None:
        # fail at deploy time, not first read: a missing dependency or
        # bad config should surface before the pipeline is running
        import aiohttp  # noqa: F401

        # rebuild the URL from the pair list so duplicate keys
        # (?ids=1&ids=2) survive; only the polling `delay` is ours
        self.url = uri.split("?", 1)[0]
        keep = [(k, v) for k, v in pairs if k != "delay"]
        if keep:
            self.url += "?" + urllib.parse.urlencode(keep)
        self.poll_delay = _duration_ms(_last(pairs, "delay", "500"), "delay") / 1000.0
        self._session = None

    async def read(self, max_records: int = 100) -> List[Record]:
        await asyncio.sleep(self.poll_delay)
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        # non-2xx responses are still emitted as records — Camel's
        # polling consumer does the same; consumers distinguish them via
        # the CamelHttpResponseCode header
        async with self._session.get(self.url) as response:
            body = await response.read()
            record = Record(
                value=body,
                headers=(
                    ("CamelHttpResponseCode", response.status),
                    ("Content-Type", response.headers.get("Content-Type", "")),
                ),
                origin=self.url,
                timestamp=now_millis(),
            )
        return [record]

    async def commit(self, records: List[Record]) -> None:
        pass

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class _KafkaEndpoint(AgentSource):
    """``kafka:topic?brokers=host:port&groupId=g`` — Camel's kafka
    component consumed through the framework's own Kafka runtime
    (topics/kafka), so the wire protocol, watermark commit, and group
    membership are the ones already tested by test_topic_contract."""

    def __init__(self, path: str, pairs: List[Tuple[str, str]]) -> None:
        from langstream_tpu.topics.kafka.runtime import (
            KafkaTopicConnectionsRuntime,
        )

        if not path:
            raise ValueError("camel-source: kafka URI needs a topic name")
        self.topic = path
        configuration: Dict[str, Any] = {
            "bootstrapServers": _last(pairs, "brokers", "127.0.0.1:9092"),
        }
        reset = _last(pairs, "autoOffsetReset", "earliest")
        configuration["autoOffsetReset"] = reset
        self._runtime = KafkaTopicConnectionsRuntime(configuration)
        self._consumer = self._runtime.create_consumer(
            "camel-source",
            {"topic": path, "group": _last(pairs, "groupId", "") or None},
        )

    async def start(self) -> None:
        await self._consumer.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        records = await self._consumer.read(max_records, timeout=0.5)
        out = []
        for record in records:
            headers = tuple(record.headers or ()) + (
                ("kafka.TOPIC", self.topic),
            )
            out.append(
                Record(
                    key=record.key,
                    value=record.value,
                    headers=headers,
                    origin=self.topic,
                    timestamp=record.timestamp,
                )
            )
            self._raw = getattr(self, "_raw", {})
            self._raw[id(out[-1])] = record
        return out

    async def commit(self, records: List[Record]) -> None:
        raw = getattr(self, "_raw", {})
        underlying = [raw.pop(id(r)) for r in records if id(r) in raw]
        if underlying:
            await self._consumer.commit(underlying)

    async def close(self) -> None:
        await self._consumer.close()
        await self._runtime.close()


class _NettyHttpEndpoint(AgentSource):
    """``netty-http:http://bind:port/path`` — Camel's netty-http
    component in consumer position is an embedded HTTP **server**:
    every incoming request becomes one record (body → value, request
    headers + method/path → headers). Responds 200 immediately —
    ingestion is asynchronous from processing, like the reference's
    Camel consumer handing exchanges to the LangStream buffer."""

    def __init__(self, path: str, pairs: List[Tuple[str, str]]) -> None:
        import aiohttp  # noqa: F401 — fail at deploy time if absent

        inner = path if "://" in path else f"http://{path}"
        parsed = urllib.parse.urlsplit(inner)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 0
        self.path = parsed.path or "/"
        self.bound_port: Optional[int] = None
        self._queue: "asyncio.Queue[Record]" = asyncio.Queue(
            maxsize=int(_last(pairs, "maxBuffered", "1000"))
        )
        self._runner = None

    async def start(self) -> None:
        from aiohttp import web

        async def handle(request):
            body = await request.read()
            headers = [
                ("CamelHttpMethod", request.method),
                ("CamelHttpPath", request.path),
                ("CamelHttpQuery", request.query_string),
            ]
            headers += [(k, v) for k, v in request.headers.items()]
            await self._queue.put(
                Record(
                    value=body,
                    headers=tuple(headers),
                    origin=request.path,
                    timestamp=now_millis(),
                )
            )
            return web.Response(status=200)

        app = web.Application()
        # accept the configured path and everything under it
        app.router.add_route("*", self.path, handle)
        if self.path != "/":
            app.router.add_route("*", self.path.rstrip("/") + "/{tail:.*}", handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.bound_port = s._server.sockets[0].getsockname()[1]  # noqa: SLF001

    async def read(self, max_records: int = 100) -> List[Record]:
        try:
            first = await asyncio.wait_for(self._queue.get(), timeout=0.5)
        except asyncio.TimeoutError:
            return []
        out = [first]
        while len(out) < max_records:
            try:
                out.append(self._queue.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out

    async def commit(self, records: List[Record]) -> None:
        pass

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()


def _file_endpoint(path: str, pairs: List[Tuple[str, str]]) -> AgentSource:
    from langstream_tpu.agents.storage import FileSource

    source = FileSource()
    source._camel_init_config = {  # consumed by CamelSourceAgent.init
        "path": path,
        "delete-objects": _flag(pairs, "delete"),
        "file-extensions": _last(pairs, "fileExtensions", ""),
        "idle-time": _duration_ms(_last(pairs, "delay", "500"), "delay") / 1000.0,
    }
    return source


def _polling_options(pairs: List[Tuple[str, str]]) -> Dict[str, Any]:
    """The option triple every object-store polling consumer shares
    (Camel spellings → native source config) — one copy, two users."""
    return {
        "delete-objects": _last(pairs, "deleteAfterRead", "true").lower()
        != "false",
        "idle-time": _duration_ms(_last(pairs, "delay", "5000"), "delay")
        / 1000.0,
        "file-extensions": _last(pairs, "fileExtensions", ""),
    }


def _s3_endpoint(path: str, pairs: List[Tuple[str, str]]) -> AgentSource:
    """``aws2-s3://bucket?accessKey=&secretKey=&region=&delay=5s&
    deleteAfterRead=false&uriEndpointOverride=http://minio:9000`` —
    Camel's aws2-s3 polling consumer mapped onto the framework's own
    :class:`agents.storage.S3Source` (SigV4 client, delete-on-commit).
    Camel option names per the aws2-s3 endpoint docs; deleteAfterRead
    defaults true there, so it does here too."""
    from langstream_tpu.agents.storage import S3Source

    bucket = path.strip("/")
    if not bucket:
        raise ValueError("camel-source: aws2-s3 URI needs a bucket name")
    endpoint = _last(pairs, "uriEndpointOverride", "")
    region = _last(pairs, "region", "us-east-1")
    source = S3Source()
    source._camel_init_config = {
        "bucketName": bucket,
        "endpoint": endpoint or f"https://s3.{region}.amazonaws.com",
        "access-key": _last(pairs, "accessKey", ""),
        "secret-key": _last(pairs, "secretKey", ""),
        "region": region,
        **_polling_options(pairs),
    }
    return source


def _azure_blob_endpoint(path: str, pairs: List[Tuple[str, str]]) -> AgentSource:
    """``azure-storage-blob://account/container?accessKey=…`` — Camel's
    azure-storage-blob consumer mapped onto
    :class:`agents.storage.AzureBlobStorageSource` (native Azure REST
    client). Path is accountName[/containerName], per the Camel
    component; connectionString / sasToken options supported."""
    from langstream_tpu.agents.storage import AzureBlobStorageSource

    account, _, container = path.strip("/").partition("/")
    connection = _last(pairs, "connectionString", "")
    if not account and not connection:
        raise ValueError(
            "camel-source: azure-storage-blob URI needs "
            "accountName/containerName (or a connectionString option)"
        )
    if not container:
        # a silent default container would poll the wrong place and
        # yield an empty stream with no clue — the consumer endpoint
        # must name its container
        raise ValueError(
            "camel-source: azure-storage-blob URI needs a container "
            "segment (azure-storage-blob://account/container)"
        )
    source = AzureBlobStorageSource()
    config: Dict[str, Any] = {
        "container": container,
        **_polling_options(pairs),
    }
    if connection:
        config["storage-account-connection-string"] = connection
    if account:
        config["storage-account-name"] = account
    access_key = _last(pairs, "accessKey", "")
    if access_key:
        config["storage-account-key"] = access_key
    sas = _last(pairs, "sasToken", "")
    if sas:
        config["sas-token"] = sas
    source._camel_init_config = config
    return source


class _PulsarEndpoint(AgentSource):
    """``pulsar:persistent://tenant/ns/topic?webServiceUrl=…&
    subscriptionName=sub`` — Camel's pulsar consumer mapped onto the
    framework's own Pulsar runtime (topics/pulsar.py, WebSocket API).
    The messaging analogue in the scheme registry: Camel's
    ``serviceUrl`` (binary protocol, pulsar://host:6650) is NOT spoken
    natively — pass ``webServiceUrl`` (the HTTP/WebSocket endpoint) or
    run the real Camel route via exec-source."""

    def __init__(self, path: str, pairs: List[Tuple[str, str]]) -> None:
        from langstream_tpu.topics.pulsar import (
            PulsarTopicConnectionsRuntime,
        )

        service = _last(pairs, "serviceUrl", "")
        web = _last(pairs, "webServiceUrl", "")
        if service and not web:
            # serviceUrl is the binary protocol by definition (pulsar://
            # or pulsar+ssl://) — consuming the default localhost web
            # endpoint instead would silently read nothing
            raise ValueError(
                "camel-source: the pulsar binary protocol "
                f"({service!r}) is not spoken natively — pass "
                "webServiceUrl=<http endpoint> (the WebSocket API), or "
                "bridge the real Camel route with exec-source"
            )
        topic = path.strip("/")
        tenant, namespace = "public", "default"
        if topic.startswith("non-persistent://"):
            # the runtime's WebSocket paths are persistent-only
            # (topics/pulsar.py _full_topic) — consuming the persistent
            # topic of the same name silently would read the wrong stream
            raise ValueError(
                "camel-source: non-persistent pulsar topics are not "
                "supported by the native runtime — use a persistent "
                "topic, or bridge the real Camel route with exec-source"
            )
        if topic.startswith("persistent://"):
            parts = topic.split("://", 1)[1].split("/")
            if len(parts) != 3:
                raise ValueError(
                    f"camel-source: bad pulsar topic {topic!r} (want "
                    "persistent://tenant/namespace/topic)"
                )
            tenant, namespace, topic = parts
        if not topic:
            raise ValueError("camel-source: pulsar URI needs a topic")
        self.topic = topic
        # the runtime owns the localhost default for a missing endpoint
        self._runtime = PulsarTopicConnectionsRuntime({
            "webServiceUrl": web,
            "tenant": tenant,
            "namespace": namespace,
        })
        self._consumer = self._runtime.create_consumer(
            "camel-source",
            {
                "topic": topic,
                "group": _last(pairs, "subscriptionName", "") or None,
            },
        )

    async def start(self) -> None:
        await self._consumer.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        return await self._consumer.read(max_records, timeout=0.5)

    async def commit(self, records: List[Record]) -> None:
        await self._consumer.commit(records)

    async def close(self) -> None:
        await self._consumer.close()
        await self._runtime.close()


# scheme → factory(path, pairs) -> AgentSource. Extensible: plugin
# packages call register_camel_scheme to map more component families.
CAMEL_SCHEMES: Dict[str, Callable[[str, List[Tuple[str, str]]], AgentSource]] = {
    "timer": _TimerEndpoint,
    "file": _file_endpoint,
    "kafka": _KafkaEndpoint,
    "netty-http": _NettyHttpEndpoint,
    "aws2-s3": _s3_endpoint,
    "azure-storage-blob": _azure_blob_endpoint,
    "pulsar": _PulsarEndpoint,
}

# what the endpoint needs in the URI path, declared ON the factory —
# the ONE source the plan-time validator reads, so runtime checks and
# plan-time guidance can't drift, and plugin schemes opt in the same
# way (timer's name may legitimately be empty)
_KafkaEndpoint.requires_path = "a topic name"
_PulsarEndpoint.requires_path = "a topic"
_s3_endpoint.requires_path = "a bucket name"
_azure_blob_endpoint.requires_path = "accountName/containerName"
_file_endpoint.requires_path = "a directory path"
_NettyHttpEndpoint.requires_path = "a bind URL"


def supported_schemes() -> List[str]:
    """All natively-mapped scheme spellings (registry + http/https) —
    the single list both the runtime error and the PLAN-TIME validator
    print, so guidance can't drift from reality."""
    return sorted(CAMEL_SCHEMES) + ["http", "https"]


def _unsupported_scheme_message(scheme: str) -> str:
    return (
        f"camel-source component {scheme!r} has no native mapping "
        f"(supported: {', '.join(supported_schemes())}); register one "
        "with langstream_tpu.agents.camel.register_camel_scheme from a "
        "plugin package (declare `expect-plugin-scheme: true` on the "
        "agent so the planner defers the check to runtime), or run the "
        "real Camel route in its own process and bridge it with "
        "exec-source (agents/connector.py)"
    )


def validate_component_uri(
    uri: str,
    options: Optional[Dict[str, Any]] = None,
    expect_plugin_scheme: bool = False,
) -> Optional[str]:
    """Plan-time check for the planner's config validation: returns an
    actionable error string for an unsupported/unparseable URI, None
    when the URI maps to a native scheme.

    The SCHEME is judged statically even when the query string carries
    unresolved placeholders (``jms:q?password=${secrets.pw}`` must still
    fail at plan time); only a placeholder in the scheme segment itself
    defers the check. ``expect_plugin_scheme`` (the agent's
    ``expect-plugin-scheme: true``) defers unknown schemes to runtime —
    plugin packages register schemes when the pod loads them, which the
    planner cannot see."""
    if not uri:
        return None
    scheme_segment = uri.partition(":")[0]
    if "${" in scheme_segment:
        return None  # resolves per-deploy
    if not isinstance(options, dict):
        options = None
    try:
        scheme, path, _pairs = parse_component_uri(uri, options)
    except ValueError as error:
        return str(error)
    if scheme in CAMEL_SCHEMES or scheme in ("http", "https"):
        # a query-only URI for a scheme that needs a path must still
        # fail at plan time ('kafka:?brokers=…' — topic forgotten).
        # The requirement lives on the factory (requires_path), one
        # source shared with the runtime checks; http/https need a URL.
        needs = (
            "a URL" if scheme in ("http", "https")
            else getattr(CAMEL_SCHEMES[scheme], "requires_path", None)
        )
        if needs and not path.strip("/"):
            return f"camel-source: {scheme} URI needs {needs} (got {uri!r})"
        return None
    if expect_plugin_scheme:
        return None
    return _unsupported_scheme_message(scheme)


def register_camel_scheme(
    scheme: str,
    factory: Callable[[str, List[Tuple[str, str]]], AgentSource],
) -> None:
    """Map an additional Camel component scheme onto a native source.
    Plugin packages (runtime/plugins.py) use this to extend the zoo.
    Set ``factory.requires_path = "<what>"`` to get the plan-time
    empty-path rejection the built-in schemes have."""
    CAMEL_SCHEMES[scheme.lower()] = factory


class CamelSourceAgent(AgentSource):
    agent_type = "camel-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        uri = configuration.get("component-uri") or ""
        self.key_header = configuration.get("key-header") or ""
        self.max_buffered = int(configuration.get("max-buffered-records", 100))
        self.scheme, path, pairs = parse_component_uri(
            uri, configuration.get("component-options")
        )
        if self.scheme in ("http", "https"):
            self._delegate: AgentSource = _HttpPollEndpoint(uri, pairs)
        elif self.scheme in CAMEL_SCHEMES:
            self._delegate = CAMEL_SCHEMES[self.scheme](path, pairs)
        else:
            # normally unreachable: the planner rejects unsupported URIs
            # at plan time with the same message (validate_component_uri)
            # — this guards direct/SDK construction and plugin schemes
            # that never got registered
            raise ValueError(
                validate_component_uri(uri)
                or _unsupported_scheme_message(self.scheme)
            )
        init_config = getattr(self._delegate, "_camel_init_config", None)
        if init_config is not None:
            await self._delegate.init(init_config)

    def __getattr__(self, name: str):
        # endpoint attributes (url, period, bound_port, …) read through
        # the facade — the pre-registry API exposed them directly
        delegate = self.__dict__.get("_delegate")
        if delegate is not None and not name.startswith("_"):
            return getattr(delegate, name)
        raise AttributeError(name)

    # ---------------------------------------------------------------- #
    async def start(self) -> None:
        await self._delegate.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        max_records = min(max_records, self.max_buffered)
        records = await self._delegate.read(max_records)
        return [self._rekey(r) for r in records]

    _MISSING = object()

    def _rekey(self, record: Record) -> Record:
        """Apply the reference's ``key-header`` rule: the named header's
        value becomes the record key."""
        if not self.key_header:
            return record
        value = record.header(self.key_header, self._MISSING)
        return record if value is self._MISSING else record.with_key(value)

    async def commit(self, records: List[Record]) -> None:
        await self._delegate.commit(records)

    async def close(self) -> None:
        if getattr(self, "_delegate", None) is not None:
            await self._delegate.close()
