"""``camel-source``: Apache Camel endpoint URIs mapped onto native
sources.

Reference: ``langstream-agent-camel/src/main/java/ai/langstream/agents/
camel/CamelSource.java:171-232`` — a generic connector escape hatch that
consumes any Camel ``component-uri`` and turns exchanges into records
(body → value, exchange headers → headers, ``key-header`` names the
header used as the record key).

The TPU build has no JVM, so the full Camel component zoo cannot run
in-process. Instead the COMMON component URIs are executed natively by
delegating to the framework's own sources, keeping pipeline definitions
portable as-is:

- ``timer:name?period=1000&repeatCount=N`` — periodic records with
  Camel's ``timer``/``firedTime`` headers;
- ``file:/dir?delete=true&fileExtensions=txt`` — directory source
  (delegates to :class:`agents.storage.FileSource`);
- ``http://…`` / ``https://…?delay=500`` — polling HTTP consumer.

Anything else raises with the honest escape hatch: run the real Camel
route in its own process via ``exec-source`` (``agents/connector.py``).
``component-options`` merge into the URI's query parameters, matching
Camel's own config layering.
"""

from __future__ import annotations

import asyncio
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from langstream_tpu.api.agent import AgentSource
from langstream_tpu.api.records import Record, now_millis


def parse_component_uri(
    uri: str, options: Optional[Dict[str, Any]] = None
) -> Tuple[str, str, List[Tuple[str, str]]]:
    """Split a Camel endpoint URI into (scheme, path, param pairs).
    Pairs preserve duplicates and valueless flags (``?delete`` keeps a
    blank value); query parameters and ``component-options`` merge,
    options appended last — Camel's own layering."""
    scheme, _, rest = uri.partition(":")
    if not scheme or not rest:
        raise ValueError(f"not a Camel endpoint URI: {uri!r}")
    path, _, query = rest.partition("?")
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    for key, value in (options or {}).items():
        pairs.append((str(key), str(value)))
    return scheme.lower(), path.strip("/") if scheme == "timer" else path, pairs


def _last(pairs: List[Tuple[str, str]], key: str, default: str) -> str:
    value = default
    for name, item in pairs:
        if name == key:
            value = item
    return value


def _flag(pairs: List[Tuple[str, str]], key: str) -> bool:
    """Boolean endpoint option: ``=true`` or a valueless ``?flag``."""
    value = _last(pairs, key, "false")
    return value == "" or value.lower() == "true"


_DURATION_UNITS = {"ms": 1.0, "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0}


def _duration_ms(value: str, key: str) -> float:
    """Camel duration syntax: plain milliseconds or a single-unit
    suffix (``5s``, ``1m``, ``250ms``)."""
    text = str(value).strip()
    for suffix in ("ms", "s", "m", "h"):
        if text.endswith(suffix):
            number = text[: -len(suffix)]
            try:
                return float(number) * _DURATION_UNITS[suffix]
            except ValueError:
                break
    try:
        return float(text)
    except ValueError:
        raise ValueError(
            f"camel-source: cannot parse {key}={value!r} (use "
            "milliseconds or a single-unit duration like 5s, 1m, 250ms)"
        ) from None


class CamelSourceAgent(AgentSource):
    agent_type = "camel-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self._delegate = None
        self._session = None
        uri = configuration.get("component-uri") or ""
        self.key_header = configuration.get("key-header") or ""
        self.max_buffered = int(configuration.get("max-buffered-records", 100))
        self.scheme, path, pairs = parse_component_uri(
            uri, configuration.get("component-options")
        )
        if self.scheme == "timer":
            self.timer_name = path
            self.period = _duration_ms(
                _last(pairs, "period", "1000"), "period"
            ) / 1000.0
            repeat = int(_last(pairs, "repeatCount", "0"))
            self.remaining = repeat if repeat > 0 else None
            self._next_fire = time.monotonic() + self.period
        elif self.scheme == "file":
            from langstream_tpu.agents.storage import FileSource

            self._delegate = FileSource()
            await self._delegate.init({
                "path": path,
                "delete-objects": _flag(pairs, "delete"),
                "file-extensions": _last(pairs, "fileExtensions", ""),
                "idle-time": _duration_ms(
                    _last(pairs, "delay", "500"), "delay"
                ) / 1000.0,
            })
        elif self.scheme in ("http", "https"):
            # rebuild the URL from the pair list so duplicate keys
            # (?ids=1&ids=2) survive; only the polling `delay` is ours
            self.url = uri.split("?", 1)[0]
            keep = [(k, v) for k, v in pairs if k != "delay"]
            if keep:
                self.url += "?" + urllib.parse.urlencode(keep)
            self.poll_delay = _duration_ms(
                _last(pairs, "delay", "500"), "delay"
            ) / 1000.0
        else:
            raise ValueError(
                f"camel-source component {self.scheme!r} has no native "
                "mapping (supported: timer, file, http, https); run the "
                "real Camel route in its own process and bridge it with "
                "exec-source (agents/connector.py)"
            )

    # ---------------------------------------------------------------- #
    async def start(self) -> None:
        if self._delegate is not None:
            await self._delegate.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        max_records = min(max_records, self.max_buffered)
        if self._delegate is not None:
            records = await self._delegate.read(max_records)
            return [self._rekey(r) for r in records]
        if self.scheme == "timer":
            return await self._read_timer()
        return await self._read_http()

    async def _read_timer(self) -> List[Record]:
        if self.remaining is not None and self.remaining <= 0:
            # exhausted: yield so the runner's poll loop never busy-spins
            await asyncio.sleep(0.2)
            return []
        delay = self._next_fire - time.monotonic()
        if delay > 0:
            # bounded sleep (not the full delay) so close() stays prompt
            await asyncio.sleep(min(delay, 0.2))
            if time.monotonic() < self._next_fire:
                return []
        self._next_fire = time.monotonic() + self.period
        if self.remaining is not None:
            self.remaining -= 1
        headers = (
            ("timer", self.timer_name), ("firedTime", now_millis()),
        )
        return [self._rekey(Record(
            value=None, headers=headers, timestamp=now_millis(),
        ))]

    async def _read_http(self) -> List[Record]:
        await asyncio.sleep(self.poll_delay)
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        async with self._session.get(self.url) as response:
            body = await response.read()
            record = Record(
                value=body,
                headers=(
                    ("CamelHttpResponseCode", response.status),
                    ("Content-Type", response.headers.get(
                        "Content-Type", "")),
                ),
                origin=self.url,
                timestamp=now_millis(),
            )
        return [self._rekey(record)]

    _MISSING = object()

    def _rekey(self, record: Record) -> Record:
        """Apply the reference's ``key-header`` rule: the named header's
        value becomes the record key."""
        if not self.key_header:
            return record
        value = record.header(self.key_header, self._MISSING)
        return record if value is self._MISSING else record.with_key(value)

    async def commit(self, records: List[Record]) -> None:
        if self._delegate is not None:
            await self._delegate.commit(records)

    async def close(self) -> None:
        if self._delegate is not None:
            await self._delegate.close()
        session = getattr(self, "_session", None)
        if session is not None:
            await session.close()
