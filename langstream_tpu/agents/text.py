"""Text-processing agents.

Equivalent of the reference's ``langstream-agents-text-processing`` module:
``text-splitter`` (``TextSplitterAgent.java:29`` — a
RecursiveCharacterTextSplitter with token-based length), ``document-to-json``
(``DocumentToJsonAgent.java:29``), ``language-detector``
(``LanguageDetectorAgent.java:27``), ``text-normaliser``, and
``text-extractor`` (Tika in the reference; here a dependency-free extractor
for text-like formats, with PDF/Office extraction gated on availability).
"""

from __future__ import annotations

import html
import html.parser
import json
import re
import unicodedata
from typing import Any, Callable, Dict, List, Optional

from langstream_tpu.api.agent import SingleRecordProcessor
from langstream_tpu.api.records import Record


# ---------------------------------------------------------------------- #
# text splitting
# ---------------------------------------------------------------------- #
class RecursiveCharacterTextSplitter:
    """Recursive splitter matching the reference's port of LangChain's
    algorithm (``textsplitter/RecursiveCharacterTextSplitter`` usage in
    ``TextSplitterAgent.java``): try separators in order, split greedily,
    merge adjacent pieces up to ``chunk_size`` with ``chunk_overlap``."""

    def __init__(
        self,
        separators: Optional[List[str]] = None,
        keep_separator: bool = False,
        chunk_size: int = 200,
        chunk_overlap: int = 100,
        length_function: Optional[Callable[[str], int]] = None,
    ) -> None:
        self.separators = separators or ["\n\n", "\n", " ", ""]
        self.keep_separator = keep_separator
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.length = length_function or len

    def split_text(self, text: str) -> List[str]:
        return self._split(text, self.separators)

    def _split(self, text: str, separators: List[str]) -> List[str]:
        final_chunks: List[str] = []
        separator = separators[-1]
        remaining = separators
        for i, candidate in enumerate(separators):
            if candidate == "" or candidate in text:
                separator = candidate
                remaining = separators[i + 1 :]
                break
        splits = self._split_on(text, separator)
        good: List[str] = []
        merge_sep = "" if self.keep_separator else separator
        for piece in splits:
            if self.length(piece) < self.chunk_size:
                good.append(piece)
            else:
                if good:
                    final_chunks.extend(self._merge(good, merge_sep))
                    good = []
                if not remaining:
                    final_chunks.append(piece)
                else:
                    final_chunks.extend(self._split(piece, remaining))
        if good:
            final_chunks.extend(self._merge(good, merge_sep))
        return final_chunks

    def _split_on(self, text: str, separator: str) -> List[str]:
        if separator == "":
            return [c for c in text]
        if self.keep_separator:
            parts = re.split(f"({re.escape(separator)})", text)
            out = [parts[i] + (parts[i + 1] if i + 1 < len(parts) else "")
                   for i in range(0, len(parts), 2)]
            return [p for p in out if p]
        return [p for p in text.split(separator) if p]

    def _merge(self, splits: List[str], separator: str) -> List[str]:
        docs: List[str] = []
        current: List[str] = []
        total = 0
        sep_len = self.length(separator)
        for piece in splits:
            piece_len = self.length(piece)
            if current and total + piece_len + sep_len > self.chunk_size:
                doc = separator.join(current).strip()
                if doc:
                    docs.append(doc)
                # pop from the left until within overlap
                while current and (
                    total > self.chunk_overlap
                    or (total + piece_len + sep_len > self.chunk_size and total > 0)
                ):
                    total -= self.length(current[0]) + sep_len
                    current.pop(0)
            current.append(piece)
            total += piece_len + sep_len
        doc = separator.join(current).strip()
        if doc:
            docs.append(doc)
        return docs


def _simple_token_length(text: str) -> int:
    """Token estimate stand-in for the reference's tiktoken cl100k_base
    (not bundled): whitespace/punctuation token count."""
    return max(1, len(re.findall(r"\w+|[^\w\s]", text)))


class TextSplitterAgent(SingleRecordProcessor):
    agent_type = "text-splitter"

    async def init(self, configuration: Dict[str, Any]) -> None:
        if configuration.get("splitter_type", "RecursiveCharacterTextSplitter") != (
            "RecursiveCharacterTextSplitter"
        ):
            raise ValueError("only RecursiveCharacterTextSplitter is supported")
        length_name = configuration.get("length_function", "cl100k_base")
        length_fn = len if length_name == "length" else _simple_token_length
        self.splitter = RecursiveCharacterTextSplitter(
            separators=configuration.get("separators", ["\n\n", "\n", " ", ""]),
            keep_separator=bool(configuration.get("keep_separator", False)),
            chunk_size=int(configuration.get("chunk_size", 200)),
            chunk_overlap=int(configuration.get("chunk_overlap", 100)),
            length_function=length_fn,
        )
        self._length = length_fn

    async def process_record(self, record: Record) -> List[Record]:
        text = record.value_as_text()
        chunks = self.splitter.split_text(text)
        out = []
        for chunk_id, chunk in enumerate(chunks):
            out.append(
                record.with_value(chunk)
                .with_header("chunk_id", str(chunk_id))
                .with_header("chunk_text_length", str(len(chunk)))
                .with_header("chunk_num_tokens", str(self._length(chunk)))
                .with_header("text_num_chunks", str(len(chunks)))
            )
        return out


# ---------------------------------------------------------------------- #
# document-to-json
# ---------------------------------------------------------------------- #
class DocumentToJsonAgent(SingleRecordProcessor):
    """Wrap the raw value into a JSON object field
    (``DocumentToJsonAgent.java:29``)."""

    agent_type = "document-to-json"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.text_field = configuration.get("text-field", "text")
        self.copy_properties = bool(configuration.get("copy-properties", True))

    async def process_record(self, record: Record) -> List[Record]:
        payload: Dict[str, Any] = {self.text_field: record.value_as_text()}
        if self.copy_properties:
            for name, value in record.headers:
                payload[name] = value
        return [record.with_value(payload)]


# ---------------------------------------------------------------------- #
# text-normaliser
# ---------------------------------------------------------------------- #
class TextNormaliserAgent(SingleRecordProcessor):
    agent_type = "text-normaliser"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.lowercase = bool(configuration.get("make-lowercase", True))
        self.trim_spaces = bool(configuration.get("trim-spaces", True))

    async def process_record(self, record: Record) -> List[Record]:
        text = record.value_as_text()
        if self.trim_spaces:
            text = re.sub(r"[ \t]+", " ", text)
            text = "\n".join(line.strip() for line in text.splitlines()).strip()
        if self.lowercase:
            text = text.lower()
        return [record.with_value(text)]


# ---------------------------------------------------------------------- #
# language detection
# ---------------------------------------------------------------------- #
_LANG_PROFILES = {
    # coarse stopword profiles; the reference uses the Lingua library
    "en": {"the", "and", "of", "to", "is", "in", "that", "it", "for", "was", "with", "are", "this", "you"},
    "es": {"el", "la", "de", "que", "y", "en", "los", "del", "las", "por", "un", "una", "es", "para"},
    "fr": {"le", "la", "de", "et", "les", "des", "est", "en", "un", "une", "du", "que", "pour", "dans"},
    "de": {"der", "die", "und", "das", "ist", "von", "den", "mit", "für", "auf", "des", "ein", "eine", "nicht"},
    "it": {"il", "la", "di", "che", "e", "un", "per", "una", "sono", "del", "non", "con", "le", "si"},
    "pt": {"o", "de", "que", "e", "do", "da", "em", "um", "para", "com", "não", "uma", "os", "no"},
}


def detect_language(text: str) -> str:
    words = set(re.findall(r"[\w']+", text.lower()))
    best, best_score = "unknown", 0
    for lang, profile in _LANG_PROFILES.items():
        score = len(words & profile)
        if score > best_score:
            best, best_score = lang, score
    return best if best_score >= 1 else "unknown"


class LanguageDetectorAgent(SingleRecordProcessor):
    """``LanguageDetectorAgent.java:27``: tag records with the detected
    language (property) so a ``when`` predicate can filter them."""

    agent_type = "language-detector"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.property = configuration.get("property", "language")
        self.allowed = configuration.get("allowedLanguages", []) or []

    async def process_record(self, record: Record) -> List[Record]:
        language = detect_language(record.value_as_text())
        if self.allowed and language not in self.allowed:
            return []
        return [record.with_header(self.property, language)]


# ---------------------------------------------------------------------- #
# text extraction
# ---------------------------------------------------------------------- #
class _HTMLTextExtractor(html.parser.HTMLParser):
    def __init__(self) -> None:
        super().__init__()
        self.parts: List[str] = []
        self._skip = 0

    def handle_starttag(self, tag, attrs):
        if tag in ("script", "style"):
            self._skip += 1

    def handle_endtag(self, tag):
        if tag in ("script", "style") and self._skip:
            self._skip -= 1

    def handle_data(self, data):
        if not self._skip and data.strip():
            self.parts.append(data.strip())


class TextExtractorAgent(SingleRecordProcessor):
    """Dependency-free extraction for text-like formats (txt/html/json/md).

    The reference uses Apache Tika (``TikaTextExtractorAgent.java:35``) with
    tesseract/libreoffice in the pod image; binary formats (PDF, DOCX) are
    out of scope for this build and produce a clear error instead of noise.
    """

    agent_type = "text-extractor"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.configuration = configuration

    async def process_record(self, record: Record) -> List[Record]:
        value = record.value
        if isinstance(value, bytes):
            if value[:4] == b"%PDF":
                raise ValueError(
                    "PDF extraction is not supported in this build "
                    "(reference uses Apache Tika); extract upstream"
                )
            value = value.decode("utf-8", errors="replace")
        text = value if isinstance(value, str) else json.dumps(value, default=str)
        lowered = text.lstrip().lower()
        if lowered.startswith(("<!doctype html", "<html")):
            extractor = _HTMLTextExtractor()
            extractor.feed(text)
            text = "\n".join(extractor.parts)
            text = html.unescape(text)
        text = unicodedata.normalize("NFC", text)
        return [record.with_value(text)]
