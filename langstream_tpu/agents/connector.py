"""Generic external-connector agents: ``exec-source`` / ``exec-sink``.

Role analogue of the reference's connector escape hatches — the Camel
source (langstream-agent-camel/src/main/java/ai/langstream/agents/camel/CamelSource.java:43)
and the Kafka Connect adapters
(langstream-kafka-runtime/.../kafkaconnect/KafkaConnect{Source,Sink}Agent.java)
— which exist to bridge arbitrary third-party systems into a pipeline.
Those ecosystems are JVM-only; the TPU build's equivalent escape hatch is
a supervised subprocess speaking newline-delimited JSON:

- ``exec-source``: spawn ``command``, each stdout line becomes a record
  (JSON object → value fields; non-JSON → raw string value). The
  process is restarted with backoff if it exits while the agent runs.
- ``exec-sink``: spawn ``command``, write each record's value as one
  JSON line to its stdin (acked once written and flushed).

This covers the same operational role (tail a syslog, bridge an MQTT
broker via mosquitto_sub, psql COPY, any CLI) without a JVM.
"""

from __future__ import annotations

import asyncio
import json
import logging
import shlex
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentSink, AgentSource
from langstream_tpu.api.records import Record, SimpleRecord, now_millis

logger = logging.getLogger(__name__)


class ExecSource(AgentSource):
    """``exec-source`` agent."""

    agent_type = "exec-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.command = configuration["command"]
        self.restart_seconds = float(configuration.get("restart-seconds", 5))
        self.parse_json = bool(configuration.get("parse-json", True))
        self.max_restarts = int(configuration.get("max-restarts", 0))  # 0 = ∞
        self._process: Optional[asyncio.subprocess.Process] = None
        self._restarts = 0

    async def start(self) -> None:
        await self._spawn()

    async def _spawn(self) -> None:
        self._process = await asyncio.create_subprocess_exec(
            *shlex.split(self.command),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL,
        )
        logger.info("exec-source started: %s (pid %s)", self.command, self._process.pid)

    async def read(self, max_records: int = 100) -> List[Record]:
        process = self._process
        if process is None or process.returncode is not None:
            if self.max_restarts and self._restarts >= self.max_restarts:
                raise RuntimeError(
                    f"exec-source command exited after {self._restarts} restarts"
                )
            self._restarts += 1
            # exponential backoff from 50 ms up to restart-seconds
            await asyncio.sleep(
                min(self.restart_seconds, 0.05 * 2 ** (self._restarts - 1))
            )
            await self._spawn()
            process = self._process
        assert process is not None and process.stdout is not None
        # await (with timeout) only the FIRST line; then drain whatever is
        # already buffered up to max_records, so high-volume subprocess
        # streams are not capped at one record per runner-loop iteration
        try:
            line = await asyncio.wait_for(process.stdout.readline(), timeout=0.5)
        except asyncio.TimeoutError:
            return []
        if not line:
            return []  # EOF; next read() restarts
        records: List[Record] = []
        while True:
            text = line.decode("utf-8", "replace").rstrip("\n")
            if text:
                value: Any = text
                if self.parse_json:
                    try:
                        value = json.loads(text)
                    except ValueError:
                        pass
                records.append(SimpleRecord(value=value, timestamp=now_millis()))
            if len(records) >= max_records:
                break
            try:
                line = await asyncio.wait_for(
                    process.stdout.readline(), timeout=0.0005
                )
            except asyncio.TimeoutError:
                break
            if not line:
                break  # EOF; next read() restarts
        return records

    async def commit(self, records: List[Record]) -> None:
        pass  # the subprocess stream has no replay; at-most-once by nature

    async def close(self) -> None:
        if self._process is not None and self._process.returncode is None:
            self._process.terminate()
            try:
                await asyncio.wait_for(self._process.wait(), timeout=5)
            except asyncio.TimeoutError:
                self._process.kill()
        self._process = None


class ExecSink(AgentSink):
    """``exec-sink`` agent."""

    agent_type = "exec-sink"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.command = configuration["command"]
        self._process: Optional[asyncio.subprocess.Process] = None

    async def start(self) -> None:
        self._process = await asyncio.create_subprocess_exec(
            *shlex.split(self.command),
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL,
        )
        logger.info("exec-sink started: %s (pid %s)", self.command, self._process.pid)

    async def write(self, record: Record) -> None:
        process = self._process
        if process is None or process.stdin is None:
            raise RuntimeError("exec-sink process not running")
        if process.returncode is not None:
            raise RuntimeError(
                f"exec-sink command exited with {process.returncode}"
            )
        value = record.value
        try:
            line = json.dumps(value, default=str)
        except TypeError:
            line = json.dumps(str(value))
        process.stdin.write(line.encode("utf-8") + b"\n")
        await process.stdin.drain()

    async def close(self) -> None:
        if self._process is not None:
            if self._process.stdin is not None:
                try:
                    self._process.stdin.close()
                except Exception:  # noqa: BLE001
                    pass
            if self._process.returncode is None:
                # stdin EOF is the drain signal: give the command real
                # time to flush what it buffered (5 s lost records on a
                # loaded host — a sink's close() must not drop data),
                # and surface the kill instead of silently discarding
                try:
                    await asyncio.wait_for(self._process.wait(), timeout=30)
                except asyncio.TimeoutError:
                    logger.warning(
                        "exec-sink command did not exit after stdin EOF; "
                        "terminating (buffered records may be lost): %s",
                        self.command,
                    )
                    self._process.terminate()
        self._process = None
