"""TPU-native vector store: brute-force exact top-k on the accelerator.

The reference delegates vector search to external engines (Cassandra/Astra,
Milvus, Pinecone, OpenSearch, Solr — ``langstream-vector-agents``). The TPU
build adds a *native* store: embeddings live in a device array and search is
one fused matmul + top_k — exact, MXU-friendly, and for corpora up to a few
million vectors faster end-to-end than a network round-trip to an ANN
service. External engines remain available through the datasource SPI.

Design for XLA:

- the corpus matrix is padded to power-of-two rows so adds don't recompile
  every step (static shapes, bucketed growth);
- scores are computed in one ``jnp.dot`` (bf16 on TPU, f32 accumulation);
- persistence is a side file (npz + jsonl metadata) written on flush, which
  doubles as the checkpoint/resume story for agent pods with disks.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from langstream_tpu.agents.datasource import DataSource


def _next_pow2(n: int) -> int:
    size = 1
    while size < n:
        size *= 2
    return size


class VectorStore:
    def __init__(
        self,
        dimensions: int,
        *,
        metric: str = "cosine",
        persist_path: Optional[str] = None,
        use_jax: bool = True,
    ) -> None:
        if metric not in ("cosine", "dot", "l2"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dimensions = dimensions
        self.metric = metric
        self.persist_path = persist_path
        self.use_jax = use_jax
        self._ids: List[str] = []
        self._index: Dict[str, int] = {}
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._matrix = np.zeros((0, dimensions), dtype=np.float32)
        self._lock = threading.Lock()
        self._search_fn_cache: Dict[int, Any] = {}
        if persist_path and os.path.exists(persist_path + ".npz"):
            self._load()

    def __len__(self) -> int:
        return len(self._ids)

    # ------------------------------------------------------------------ #
    # mutation
    # ------------------------------------------------------------------ #
    def upsert(
        self,
        doc_id: str,
        vector: List[float],
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        array = np.asarray(vector, dtype=np.float32)
        if array.shape != (self.dimensions,):
            raise ValueError(
                f"vector has shape {array.shape}, store expects ({self.dimensions},)"
            )
        if self.metric == "cosine":
            norm = float(np.linalg.norm(array)) or 1.0
            array = array / norm
        with self._lock:
            row = self._index.get(doc_id)
            if row is None:
                row = len(self._ids)
                self._ids.append(doc_id)
                self._index[doc_id] = row
                if row >= self._matrix.shape[0]:
                    grown = np.zeros(
                        (_next_pow2(row + 1), self.dimensions), dtype=np.float32
                    )
                    grown[: self._matrix.shape[0]] = self._matrix
                    self._matrix = grown
            self._matrix[row] = array
            self._meta[doc_id] = metadata or {}

    def delete(self, doc_id: str) -> bool:
        with self._lock:
            row = self._index.pop(doc_id, None)
            if row is None:
                return False
            last = len(self._ids) - 1
            last_id = self._ids[last]
            # swap-delete keeps the matrix dense
            self._matrix[row] = self._matrix[last]
            self._matrix[last] = 0.0
            self._ids[row] = last_id
            self._ids.pop()
            if last_id != doc_id:
                self._index[last_id] = row
            self._meta.pop(doc_id, None)
            return True

    # ------------------------------------------------------------------ #
    # search
    # ------------------------------------------------------------------ #
    def search(
        self, vector: List[float], top_k: int = 10
    ) -> List[Dict[str, Any]]:
        count = len(self._ids)
        if count == 0:
            return []
        query = np.asarray(vector, dtype=np.float32)
        if self.metric == "cosine":
            norm = float(np.linalg.norm(query)) or 1.0
            query = query / norm
        k = min(top_k, count)
        padded_rows = self._matrix.shape[0]
        if self.use_jax:
            scores, indices = self._search_jax(query, k, padded_rows, count)
        else:
            scores, indices = self._search_numpy(query, k, count)
        out = []
        for score, row in zip(scores, indices):
            doc_id = self._ids[int(row)]
            record = {"id": doc_id, "similarity": float(score)}
            record.update(self._meta.get(doc_id, {}))
            out.append(record)
        return out

    def _search_numpy(self, query, k, count):
        matrix = self._matrix[:count]
        if self.metric == "l2":
            scores = -np.linalg.norm(matrix - query, axis=1)
        else:
            scores = matrix @ query
        order = np.argsort(-scores)[:k]
        return scores[order], order

    def _search_jax(self, query, k, padded_rows, count):
        import jax
        import jax.numpy as jnp

        key = (padded_rows, k, self.metric)
        fn = self._search_fn_cache.get(key)
        if fn is None:

            @jax.jit
            def _run(matrix, q, valid):
                if self.metric == "l2":
                    scores = -jnp.sum((matrix - q) ** 2, axis=1)
                else:
                    scores = matrix @ q
                # mask padding rows out of the ranking
                scores = jnp.where(
                    jnp.arange(matrix.shape[0]) < valid, scores, -jnp.inf
                )
                return jax.lax.top_k(scores, k)

            fn = _run
            self._search_fn_cache[key] = fn
        scores, indices = fn(self._matrix, query, count)
        return np.asarray(scores), np.asarray(indices)

    # ------------------------------------------------------------------ #
    # persistence (checkpoint/resume for agents with disks)
    # ------------------------------------------------------------------ #
    def flush(self) -> None:
        if not self.persist_path:
            return
        os.makedirs(os.path.dirname(self.persist_path) or ".", exist_ok=True)
        count = len(self._ids)
        np.savez_compressed(
            self.persist_path + ".npz", matrix=self._matrix[:count]
        )
        with open(self.persist_path + ".meta.json", "w", encoding="utf-8") as f:
            json.dump(
                {"ids": self._ids, "meta": self._meta, "metric": self.metric},
                f,
                ensure_ascii=False,
                default=str,
            )

    def _load(self) -> None:
        data = np.load(self.persist_path + ".npz")
        matrix = data["matrix"]
        with open(self.persist_path + ".meta.json", "r", encoding="utf-8") as f:
            payload = json.load(f)
        self._ids = list(payload["ids"])
        self._meta = dict(payload["meta"])
        self._index = {doc_id: i for i, doc_id in enumerate(self._ids)}
        rows = _next_pow2(max(1, matrix.shape[0]))
        self._matrix = np.zeros((rows, self.dimensions), dtype=np.float32)
        self._matrix[: matrix.shape[0]] = matrix


_SHARED_STORES: Dict[str, VectorStore] = {}
_SHARED_LOCK = threading.Lock()


def shared_store(name: str, dimensions: int, **kwargs) -> VectorStore:
    """Named stores shared across agents of one process (writer agent and
    query agent see the same corpus, like a shared external DB)."""
    with _SHARED_LOCK:
        store = _SHARED_STORES.get(name)
        if store is None:
            store = VectorStore(dimensions, **kwargs)
            _SHARED_STORES[name] = store
        return store


class VectorStoreDataSource(DataSource):
    """Datasource adapter: JSON query specs against a named store.

    Query spec: ``{"action": "search", "vector": ?, "top-k": 5}`` or
    ``{"action": "upsert", "id": ?, "vector": ?, "metadata": {...}}`` —
    ``?`` placeholders fill from params in order.
    """

    def __init__(self, config: Dict[str, Any]) -> None:
        self.store = shared_store(
            config.get("name", "default"),
            int(config.get("dimensions", 384)),
            metric=config.get("metric", "cosine"),
            persist_path=config.get("persist-path"),
        )

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = _fill(query, params)
        action = spec.get("action", "search")
        if action != "search":
            raise ValueError("vector datasource query only supports 'search'")
        return self.store.search(spec["vector"], int(spec.get("top-k", 10)))

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = _fill(statement, params)
        action = spec.get("action")
        if action == "upsert":
            self.store.upsert(str(spec["id"]), spec["vector"], spec.get("metadata"))
            self.store.flush()
            return {"rowcount": 1}
        if action == "delete":
            deleted = self.store.delete(str(spec["id"]))
            self.store.flush()
            return {"rowcount": int(deleted)}
        raise ValueError(f"unsupported vector action {action!r}")


def _fill(query: str, params: List[Any]) -> Dict[str, Any]:
    from langstream_tpu.agents.datasource import _substitute

    return json.loads(_substitute(query, params))
