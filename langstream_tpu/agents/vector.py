"""Vector DB agents: write embeddings, query for RAG.

Equivalent of the reference's ``langstream-vector-agents``
(``VectorDBSinkAgent.java:28``, ``QueryVectorDBAgent.java:39``): a sink that
writes records into a vector datasource and a processor that queries one.
Both speak the datasource SPI, so they work against the TPU-native store
(``agents/vectorstore.py``) or any future external engine adapter.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentSink, SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.agents.datasource import DataSourceRegistry
from langstream_tpu.agents.el import Expression
from langstream_tpu.agents.transform import TransformContext


class VectorDBSinkAgent(AgentSink):
    """Write each record into a vector datasource.

    Config: ``datasource`` (resource name), plus field expressions
    ``vector.id`` / ``vector.vector`` / ``vector.metadata`` (reference
    config shape for the Astra/Milvus writers).
    """

    agent_type = "vector-db-sink"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.datasource_name = configuration.get("datasource", "datasource")
        self.id_expr = Expression(configuration.get("vector.id", "fn.uuid()"))
        self.vector_expr = Expression(
            configuration.get("vector.vector", "value.embeddings")
        )
        metadata = configuration.get("vector.metadata")
        self.metadata_expr = Expression(metadata) if metadata else None
        self.text_expr = (
            Expression(configuration.get("vector.text"))
            if configuration.get("vector.text")
            else None
        )
        self._registry: Optional[DataSourceRegistry] = None
        self._datasource = None

    async def start(self) -> None:
        self._registry = DataSourceRegistry(getattr(self.context, "resources", {}))
        self._datasource = self._registry.resolve(self.datasource_name)

    async def close(self) -> None:
        if self._registry is not None:
            await self._registry.close()

    async def write(self, record: Record) -> None:
        el_ctx = TransformContext(record).el_context()
        doc_id = self.id_expr.evaluate(el_ctx)
        vector = self.vector_expr.evaluate(el_ctx)
        if vector is None:
            raise ValueError(
                "record has no embeddings vector for vector-db-sink "
                "(compute-ai-embeddings upstream?)"
            )
        metadata: Dict[str, Any] = {}
        if self.metadata_expr is not None:
            metadata = dict(self.metadata_expr.evaluate(el_ctx) or {})
        if self.text_expr is not None:
            metadata["text"] = self.text_expr.evaluate(el_ctx)
        statement = json.dumps(
            {"action": "upsert", "id": str(doc_id), "vector": list(vector),
             "metadata": metadata}
        )
        await self._datasource.execute(statement, [])


class QueryVectorDBAgent(SingleRecordProcessor):
    """Query a vector datasource, put results in ``output-field``
    (``QueryVectorDBAgent.java:39``)."""

    agent_type = "query-vector-db"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.datasource_name = configuration.get("datasource", "datasource")
        self.query = configuration["query"]
        self.fields = [Expression(f) for f in configuration.get("fields", [])]
        self.output_field = configuration.get("output-field", "value.query-result")
        self.only_first = bool(configuration.get("only-first", False))
        self._registry: Optional[DataSourceRegistry] = None
        self._datasource = None

    async def start(self) -> None:
        self._registry = DataSourceRegistry(getattr(self.context, "resources", {}))
        self._datasource = self._registry.resolve(self.datasource_name)

    async def close(self) -> None:
        if self._registry is not None:
            await self._registry.close()

    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        el_ctx = ctx.el_context()
        params = [f.evaluate(el_ctx) for f in self.fields]
        rows = await self._datasource.query(self.query, params)
        result: Any = rows[0] if (self.only_first and rows) else rows
        ctx.set_field(self.output_field, result)
        return [ctx.to_record()]
