"""Web crawler source with checkpointed status.

Equivalent of the reference's ``langstream-agent-webcrawler``
(``WebCrawlerSource.java:62`` + ``crawler/WebCrawler.java:51``): crawl seed
URLs within allowed domains, respect robots.txt, emit one record per page,
and checkpoint crawl status (visited set + frontier) so a restarted agent
resumes where it stopped — the reference persists to S3 or the agent disk
(``WebCrawlerSource.java:381-440``); here the agent's persistent state
directory (``StatusStorage`` contract).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import urllib.parse
import urllib.robotparser
from typing import Any, Dict, List, Optional, Set

from langstream_tpu.api.agent import AgentSource
from langstream_tpu.api.records import Record

logger = logging.getLogger(__name__)


class WebCrawlerSource(AgentSource):
    agent_type = "webcrawler-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.seed_urls: List[str] = list(configuration.get("seed-urls", []))
        self.allowed_domains: List[str] = list(configuration.get("allowed-domains", []))
        self.forbidden_paths: List[str] = list(configuration.get("forbidden-paths", []))
        self.max_urls = int(configuration.get("max-urls", 1000))
        self.min_time_between_requests = (
            float(configuration.get("min-time-between-requests", 500)) / 1000.0
        )
        self.user_agent = configuration.get("user-agent", "langstream-tpu-crawler")
        self.handle_robots = bool(configuration.get("handle-robots-file", True))
        self.max_depth = int(configuration.get("max-depth", 50))
        self._frontier: List[Dict[str, Any]] = []
        self._visited: Set[str] = set()
        self._robots: Dict[str, urllib.robotparser.RobotFileParser] = {}
        self._session = None
        self._status_path: Optional[str] = None

    async def start(self) -> None:
        state_dir = self.context.persistent_state_directory()
        if state_dir:
            self._status_path = os.path.join(state_dir, "webcrawler.status.json")
            self._load_status()
        if not self._frontier and not self._visited:
            self._frontier = [{"url": url, "depth": 0} for url in self.seed_urls]
        import aiohttp

        self._session = aiohttp.ClientSession(
            headers={"User-Agent": self.user_agent}
        )

    async def close(self) -> None:
        self._save_status()
        if self._session is not None:
            await self._session.close()

    # -- status checkpointing (StatusStorage contract) ------------------ #
    def _load_status(self) -> None:
        if self._status_path and os.path.exists(self._status_path):
            with open(self._status_path, "r", encoding="utf-8") as handle:
                status = json.load(handle)
            self._visited = set(status.get("visited", []))
            self._frontier = list(status.get("frontier", []))
            logger.info(
                "resumed crawl: %d visited, %d queued",
                len(self._visited),
                len(self._frontier),
            )

    def _save_status(self) -> None:
        if not self._status_path:
            return
        with open(self._status_path, "w", encoding="utf-8") as handle:
            json.dump(
                {"visited": sorted(self._visited), "frontier": self._frontier},
                handle,
            )

    # -- crawling -------------------------------------------------------- #
    def _allowed(self, url: str) -> bool:
        parsed = urllib.parse.urlparse(url)
        if parsed.scheme not in ("http", "https"):
            return False
        if self.allowed_domains and not any(
            parsed.netloc == d or parsed.netloc.endswith("." + d)
            or url.startswith(d)
            for d in self.allowed_domains
        ):
            return False
        if any(parsed.path.startswith(p) for p in self.forbidden_paths):
            return False
        return True

    async def _robots_allows(self, url: str) -> bool:
        if not self.handle_robots:
            return True
        parsed = urllib.parse.urlparse(url)
        base = f"{parsed.scheme}://{parsed.netloc}"
        parser = self._robots.get(base)
        if parser is None:
            parser = urllib.robotparser.RobotFileParser()
            try:
                async with self._session.get(
                    base + "/robots.txt", timeout=10
                ) as response:
                    if response.status == 200:
                        parser.parse((await response.text()).splitlines())
                    else:
                        parser.allow_all = True
            except Exception:  # noqa: BLE001 — no robots file = allow
                parser.allow_all = True
            self._robots[base] = parser
        return parser.can_fetch(self.user_agent, url)

    def _extract_links(self, base_url: str, html_text: str) -> List[str]:
        from bs4 import BeautifulSoup

        soup = BeautifulSoup(html_text, "html.parser")
        links = []
        for anchor in soup.find_all("a", href=True):
            href = urllib.parse.urljoin(base_url, anchor["href"])
            href = urllib.parse.urldefrag(href).url
            links.append(href)
        return links

    async def read(self, max_records: int = 100) -> List[Record]:
        if not self._frontier or len(self._visited) >= self.max_urls:
            await asyncio.sleep(1.0)
            return []
        entry = self._frontier.pop(0)
        url, depth = entry["url"], int(entry.get("depth", 0))
        if url in self._visited or not self._allowed(url):
            return []
        self._visited.add(url)
        if not await self._robots_allows(url):
            return []
        await asyncio.sleep(self.min_time_between_requests)
        try:
            async with self._session.get(url, timeout=30) as response:
                if response.status != 200:
                    logger.info("skipping %s: HTTP %d", url, response.status)
                    return []
                content_type = response.headers.get("Content-Type", "")
                body = await response.read()
        except Exception as error:  # noqa: BLE001 — crawl on
            logger.warning("error fetching %s: %s", url, error)
            return []
        if "html" in content_type and depth < self.max_depth:
            try:
                links = self._extract_links(url, body.decode("utf-8", "replace"))
                for link in links:
                    if link not in self._visited and self._allowed(link):
                        self._frontier.append({"url": link, "depth": depth + 1})
            except Exception:  # noqa: BLE001
                pass
        self._save_status()
        return [
            Record(
                value=body,
                key=url,
                headers=(("url", url), ("content_type", content_type)),
            )
        ]

    async def commit(self, records: List[Record]) -> None:
        self._save_status()
