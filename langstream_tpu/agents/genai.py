"""The GenAI toolkit agent: executes a list of declarative steps.

Equivalent of the reference's ``GenAIToolKitAgent``
(``langstream-agents/langstream-ai-agents/src/main/java/ai/langstream/ai/agents/GenAIToolKitAgent.java:53``)
and its step implementations under ``com/datastax/oss/streaming/ai/``
(dispatch table ``util/TransformFunctionUtil.java:166-216``). The planner
compiles every declarative step type (``drop-fields``, ``compute``,
``ai-chat-completions``, ...) into one node of this executor with a
``steps`` list; each step mutates a :class:`TransformContext` and may carry
a ``when`` predicate.

Streaming parity (``ChatCompletionsStep.java:42,126-190``): chunk records
copy the source context, set ``stream-id`` / ``stream-index`` /
``stream-last-message`` headers, write the delta into
``stream-response-completion-field`` (or ``completion-field``) and go to
``stream-to-topic`` immediately; the final full answer lands in
``completion-field`` on the main record. Exponential chunk batching
(1, 2, 4, ... up to ``min-chunks-per-message``,
``OpenAICompletionService.java:126,290-300``) is implemented here on the
caller side so every provider streams identically.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentContext, SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.api.service import ChatChunk, ChatMessage, StreamingChunksConsumer
from langstream_tpu.agents.el import Expression, render_template
from langstream_tpu.agents.transform import TransformContext
from langstream_tpu.runtime.batching import BatchExecutor

logger = logging.getLogger(__name__)


class Step:
    """One transform step; subclasses mutate the context in ``apply``."""

    def __init__(self, config: Dict[str, Any], agent: "GenAIToolKitAgent") -> None:
        self.config = config
        self.agent = agent
        when = config.get("when")
        self._when = Expression(when) if when else None

    async def start(self) -> None:
        ...

    async def close(self) -> None:
        ...

    def should_apply(self, ctx: TransformContext) -> bool:
        if self._when is None:
            return True
        return bool(self._when.evaluate(ctx.el_context()))

    async def apply(self, ctx: TransformContext) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------- #
# structural steps (CastStep, DropStep, DropFieldStep, FlattenStep,
# MergeKeyValueStep, UnwrapKeyValueStep, ComputeStep in the reference)
# ---------------------------------------------------------------------- #
class DropStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        ctx.dropped = True


class DropFieldsStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        part = self.config.get("part")  # None = both, like the reference
        for field in self.config.get("fields", []):
            if "." in field:
                ctx.delete_field(field)
                continue
            if part in (None, "value"):
                ctx.delete_field(f"value.{field}")
            if part in (None, "key"):
                ctx.delete_field(f"key.{field}")


class MergeKeyValueStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        key = ctx._structured(ctx.key)
        value = ctx._structured(ctx.value)
        if isinstance(key, dict) and isinstance(value, dict):
            ctx.value = {**key, **value}


class UnwrapKeyValueStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        if self.config.get("unwrapKey", self.config.get("unwrap-key", False)):
            ctx.value = ctx.key
        # else: value stays the value (drops the key pairing)
        ctx.key = None


class CastStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        schema_type = self.config.get("schema-type", "string")
        part = self.config.get("part", "value")
        current = ctx.get_field(part)
        ctx.set_field(part, _cast(current, schema_type))


def _cast(value: Any, schema_type: str) -> Any:
    if value is None:
        return None
    # the reference's type names are case-insensitive in practice: compute
    # fields use upper-case (ComputeFieldType.java:19, examples use
    # `type: STRING`) while cast uses lower-case schema-type values
    schema_type = str(schema_type).lower()
    if schema_type == "string":
        if isinstance(value, (dict, list)):
            return json.dumps(value, ensure_ascii=False, default=str)
        if isinstance(value, bytes):
            return value.decode("utf-8", errors="replace")
        return str(value)
    if schema_type in ("int32", "int64", "int"):
        return int(float(value))
    if schema_type in ("float", "double"):
        return float(value)
    if schema_type == "boolean":
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes")
        return bool(value)
    if schema_type == "bytes":
        return value if isinstance(value, bytes) else str(value).encode("utf-8")
    if schema_type == "json":
        return json.loads(value) if isinstance(value, (str, bytes)) else value
    raise ValueError(f"unknown schema-type {schema_type!r}")


class FlattenStep(Step):
    async def apply(self, ctx: TransformContext) -> None:
        delimiter = self.config.get("delimiter", "_")
        part = self.config.get("part")
        if part in (None, "value"):
            value = ctx._structured(ctx.value)
            if isinstance(value, dict):
                ctx.value = _flatten(value, delimiter)
        if part in (None, "key"):
            key = ctx._structured(ctx.key)
            if isinstance(key, dict):
                ctx.key = _flatten(key, delimiter)


def _flatten(mapping: Dict[str, Any], delimiter: str, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, value in mapping.items():
        full = f"{prefix}{delimiter}{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_flatten(value, delimiter, full))
        else:
            out[full] = value
    return out


class ComputeStep(Step):
    def __init__(self, config, agent) -> None:
        super().__init__(config, agent)
        self._fields = []
        for field in config.get("fields", []):
            self._fields.append(
                (
                    field["name"],
                    Expression(str(field["expression"])),
                    field.get("type"),
                    field.get("optional", False),
                )
            )

    async def apply(self, ctx: TransformContext) -> None:
        el_ctx = ctx.el_context()
        computed = []
        for name, expression, field_type, optional in self._fields:
            value = expression.evaluate(el_ctx)
            if value is None and optional:
                continue
            if field_type:
                value = _cast(value, field_type)
            computed.append((name, value))
        for name, value in computed:
            ctx.set_field(name, value)


# ---------------------------------------------------------------------- #
# AI steps
# ---------------------------------------------------------------------- #
class ComputeAIEmbeddingsStep(Step):
    """Micro-batched embeddings (``ComputeAIEmbeddingsStep.java:46``):
    records coalesce through a batch executor into one padded device call;
    per-key ordering is the runner's concern, batching is ours."""

    def __init__(self, config, agent) -> None:
        super().__init__(config, agent)
        self.text_template = config.get("text", "{{ value }}")
        self.embeddings_field = config.get("embeddings-field", "value.embeddings")
        self.model = config.get("model")
        self.batch_size = int(config.get("batch-size", 10))
        # reference default flush-interval: 0 = immediate; we keep a small
        # linger so concurrent records in the same poll coalesce
        self.flush_interval = float(config.get("flush-interval", 0.01))
        self._executor: Optional[BatchExecutor] = None
        self._service = None

    async def start(self) -> None:
        registry = self.agent.service_registry()
        self._service = registry.embeddings(
            self.config.get("ai-service"), model=self.model
        )
        self._executor = BatchExecutor(
            self.batch_size, self._process_batch, flush_interval=self.flush_interval
        )

    async def _process_batch(self, items: List[Any]) -> None:
        texts = [text for text, _future in items]
        try:
            vectors = await self._service.compute_embeddings(texts)
            if len(vectors) != len(items):
                raise ValueError(
                    f"embeddings service returned {len(vectors)} vectors "
                    f"for {len(items)} texts"
                )
            for (_text, future), vector in zip(items, vectors):
                if not future.done():
                    future.set_result(vector)
        except BaseException as error:  # noqa: BLE001 — routed per record
            for _text, future in items:
                if not future.done():
                    future.set_exception(error)

    async def apply(self, ctx: TransformContext) -> None:
        text = render_template(self.text_template, ctx.el_context())
        future = asyncio.get_running_loop().create_future()
        await self._executor.add((text, future))
        vector = await future
        ctx.set_field(self.embeddings_field, vector)

    async def close(self) -> None:
        if self._executor is not None:
            await self._executor.close()


class QueryStep(Step):
    """Datasource query (``QueryStep.java:35``): ``fields`` evaluate to
    params, results land in ``output-field``."""

    def __init__(self, config, agent) -> None:
        super().__init__(config, agent)
        self.query = config["query"]
        self.output_field = config.get("output-field", "value.query-result")
        self.only_first = bool(config.get("only-first", False))
        self.mode = config.get("mode", "query")  # query | execute
        self._fields = [Expression(f) for f in config.get("fields", [])]
        self._datasource = None

    async def start(self) -> None:
        self._datasource = self.agent.datasource_registry().resolve(
            self.config.get("datasource", "datasource")
        )

    async def apply(self, ctx: TransformContext) -> None:
        el_ctx = ctx.el_context()
        params = [f.evaluate(el_ctx) for f in self._fields]
        if self.mode == "execute":
            result: Any = await self._datasource.execute(self.query, params)
        else:
            rows = await self._datasource.query(self.query, params)
            result = rows[0] if (self.only_first and rows) else rows
        ctx.set_field(self.output_field, result)


class _ChunkBatcher(StreamingChunksConsumer):
    """Exponential chunk batching: emit after 1, 2, 4, ... accumulated
    chunks up to ``min_chunks``, then every ``min_chunks``
    (``OpenAICompletionService.java:126,290-300``)."""

    def __init__(self, min_chunks: int, emit) -> None:
        self.min_chunks = max(1, min_chunks)
        self.emit = emit  # (answer_id, index, text, last) -> None
        self._threshold = 1
        self._buffer: List[str] = []
        self._out_index = 0

    def consume_chunk(self, answer_id: str, index: int, chunk: ChatChunk, last: bool) -> None:
        self._buffer.append(chunk.content)
        if last or len(self._buffer) >= self._threshold:
            text = "".join(self._buffer)
            self._buffer = []
            if self._threshold < self.min_chunks:
                self._threshold = min(self._threshold * 2, self.min_chunks)
            if text or last:
                self.emit(answer_id, self._out_index, text, last)
                self._out_index += 1


class ChatCompletionsStep(Step):
    """``ChatCompletionsStep.java:42`` — prompt templating, streaming, and
    result/log field mapping."""

    KIND = "chat"

    def __init__(self, config, agent) -> None:
        super().__init__(config, agent)
        self.completion_field = config.get("completion-field", "value")
        self.log_field = config.get("log-field")
        self.stream_to_topic = config.get("stream-to-topic")
        self.stream_response_field = config.get("stream-response-completion-field")
        self.min_chunks = int(config.get("min-chunks-per-message", 20))
        self.want_logprobs = bool(config.get("logprobs"))
        self.logprobs_field = config.get("logprobs-field", "value.logprobs")
        self.tokens_field = config.get("tokens-field", "value.tokens")
        self.messages = config.get("messages", [])
        self.prompt = config.get("prompt", [])
        self._service = None
        self._stream_producer = None
        self._options = {
            key: config.get(key)
            for key in (
                "model", "max-tokens", "temperature", "top-p", "top-k",
                "stop", "presence-penalty", "frequency-penalty", "seed",
                "logit-bias",
                "session-field",
            )
            if config.get(key) is not None
        }

    async def start(self) -> None:
        registry = self.agent.service_registry()
        self._service = registry.completions(self.config.get("ai-service"))
        if self.stream_to_topic:
            self._stream_producer = self.agent.topic_producer(self.stream_to_topic)
            await self._stream_producer.start()

    async def close(self) -> None:
        if self._stream_producer is not None:
            await self._stream_producer.close()

    def _render_messages(self, el_ctx: Dict[str, Any]) -> List[ChatMessage]:
        if self.KIND == "chat":
            return [
                ChatMessage(
                    role=m.get("role", "user"),
                    content=render_template(m.get("content", ""), el_ctx),
                )
                for m in self.messages
            ]
        prompts = self.prompt if isinstance(self.prompt, list) else [self.prompt]
        return [ChatMessage("user", render_template(p, el_ctx)) for p in prompts]

    async def apply(self, ctx: TransformContext) -> None:
        el_ctx = ctx.el_context()
        messages = self._render_messages(el_ctx)
        consumer = None
        loop = asyncio.get_running_loop()
        stream_tasks: List[asyncio.Task] = []
        if self._stream_producer is not None:

            def emit(answer_id: str, index: int, text: str, last: bool) -> None:
                chunk_record = self._make_chunk_record(ctx, answer_id, index, text, last)
                stream_tasks.append(
                    loop.create_task(self._stream_producer.write(chunk_record))
                )

            consumer = _ChunkBatcher(self.min_chunks, emit)

        options = dict(self._options)
        options["min-chunks-per-message"] = self.min_chunks
        if self.want_logprobs:
            options["logprobs"] = True
        # session affinity for KV-cache reuse (BASELINE config #5): the
        # gateway's session header, else the record key (broker partitioning
        # by key then gives replica affinity too)
        session = ctx.properties.get("langstream-client-session-id")
        if session is None and ctx.record.key is not None:
            session = str(ctx.record.key)
        if session is not None:
            options["session-id"] = session
        # end-to-end trace context: the gateway's trace header rides the
        # record into the engine's per-request spans (TTFT/TPOT land in
        # the same timeline as the gateway/runner spans)
        from langstream_tpu.runtime.tracing import TRACE_ID_HEADER

        trace_id = ctx.properties.get(TRACE_ID_HEADER)
        if trace_id:
            options["trace-id"] = str(trace_id)
        if self.KIND == "text":
            # verbatim continuation, no chat template (reference:
            # TextCompletionsStep calls getTextCompletions)
            result = await self._service.get_text_completions(
                [m.content for m in messages], options, consumer
            )
        else:
            result = await self._service.get_chat_completions(
                messages, options, consumer
            )
        for task in stream_tasks:
            await task
        ctx.set_field(self.completion_field, result.content)
        if self.want_logprobs and result.logprobs is not None:
            # OpenAI-style logprobs surface: the flare-controller's
            # tokens-field/logprobs-field defaults resolve against these
            ctx.set_field(self.tokens_field, list(result.tokens or []))
            ctx.set_field(self.logprobs_field, list(result.logprobs))
        if self.log_field:
            ctx.set_field(
                self.log_field,
                json.dumps(
                    {
                        "model": self._options.get("model"),
                        "options": {k: v for k, v in options.items()},
                        "messages": [
                            {"role": m.role, "content": m.content} for m in messages
                        ],
                    },
                    ensure_ascii=False,
                ),
            )

    def _make_chunk_record(
        self, ctx: TransformContext, answer_id: str, index: int, text: str, last: bool
    ) -> Record:
        # deep-copy the context per chunk (ChatCompletionsStep.java:139-150):
        # chunk records must not alias the live value dict of the main record
        import copy as _copymod

        copy = TransformContext(ctx.record)
        copy.key = _copymod.deepcopy(ctx.key)
        copy.value = _copymod.deepcopy(ctx.value)
        copy.properties = dict(ctx.properties)
        copy.properties["stream-id"] = answer_id
        copy.properties["stream-index"] = str(index)
        copy.properties["stream-last-message"] = str(last).lower()
        field = self.stream_response_field or self.completion_field
        copy.set_field(field, text)
        return copy.to_record()


class TextCompletionsStep(ChatCompletionsStep):
    """``ai-text-completions``: prompt list instead of chat messages."""

    KIND = "text"

    async def apply(self, ctx: TransformContext) -> None:
        await super().apply(ctx)


_STEP_TYPES = {
    "drop": DropStep,
    "drop-fields": DropFieldsStep,
    "merge-key-value": MergeKeyValueStep,
    "unwrap-key-value": UnwrapKeyValueStep,
    "cast": CastStep,
    "flatten": FlattenStep,
    "compute": ComputeStep,
    "compute-ai-embeddings": ComputeAIEmbeddingsStep,
    "query": QueryStep,
    "ai-chat-completions": ChatCompletionsStep,
    "ai-text-completions": TextCompletionsStep,
}


class GenAIToolKitAgent(SingleRecordProcessor):
    """Executes the compiled ``steps`` list for each record."""

    agent_type = "ai-tools"
    agent_id = "ai-tools"

    def __init__(self) -> None:
        self.steps: List[Step] = []
        self._service_registry = None
        self._datasource_registry = None

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.configuration = configuration
        for step_config in configuration.get("steps", []):
            step_type = step_config.get("type")
            step_cls = _STEP_TYPES.get(step_type)
            if step_cls is None:
                raise ValueError(
                    f"unknown GenAI step type {step_type!r}; "
                    f"known: {sorted(_STEP_TYPES)}"
                )
            self.steps.append(step_cls(step_config, self))

    async def start(self) -> None:
        for step in self.steps:
            await step.start()

    async def close(self) -> None:
        for step in self.steps:
            await step.close()

    # -- wiring helpers used by steps --------------------------------- #
    def service_registry(self):
        if self._service_registry is None:
            from langstream_tpu.providers.registry import ServiceProviderRegistry

            resources = getattr(self.context, "resources", {}) or {}
            shared = getattr(self.context, "service_provider_registry", None)
            self._service_registry = shared or ServiceProviderRegistry(resources)
        return self._service_registry

    def datasource_registry(self):
        if self._datasource_registry is None:
            from langstream_tpu.agents.datasource import DataSourceRegistry

            resources = getattr(self.context, "resources", {}) or {}
            self._datasource_registry = DataSourceRegistry(resources)
        return self._datasource_registry

    def topic_producer(self, topic: str):
        connections = getattr(self.context, "topic_connections", None)
        if connections is None:
            raise ValueError(
                "stream-to-topic requires a topic runtime in the agent context"
            )
        return connections.create_producer(self.agent_id, {"topic": topic})

    def agent_info(self) -> Dict[str, Any]:
        return {
            "agent-id": self.agent_id,
            "agent-type": self.agent_type,
            "component-type": "processor",
            "steps": [s.config.get("type") for s in self.steps],
        }

    # -- record path --------------------------------------------------- #
    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        for step in self.steps:
            if not step.should_apply(ctx):
                continue
            await step.apply(ctx)
            if ctx.dropped:
                return []
        out = ctx.to_record()
        if ctx.destination_topic:
            out = out.with_header("langstream-destination", ctx.destination_topic)
        return [out]
