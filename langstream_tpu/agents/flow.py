"""Flow-control agents: dispatch, timer-source, trigger-event, log-event.

Equivalent of the reference's ``langstream-agents-flow-control`` module
(type map ``flow/FlowControlAgentsCodeProvider.java:26-34``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentContext, AgentSource, SingleRecordProcessor
from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.agents.el import Expression
from langstream_tpu.agents.transform import TransformContext

logger = logging.getLogger(__name__)


class DispatchAgent(SingleRecordProcessor):
    """Route records to other topics by condition (``dispatch`` agent).

    Config: ``routes: [{when, destination, action: dispatch|drop}]``.
    A record matching a ``dispatch`` route is written to that topic and
    swallowed; ``drop`` discards it; no match → pass through.
    """

    agent_type = "dispatch"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.routes = []
        for route in configuration.get("routes", []):
            self.routes.append(
                (
                    Expression(route["when"]) if route.get("when") else None,
                    route.get("destination"),
                    route.get("action", "dispatch"),
                )
            )
        self._producers: Dict[str, Any] = {}

    async def close(self) -> None:
        for producer in self._producers.values():
            await producer.close()

    async def _producer(self, topic: str):
        producer = self._producers.get(topic)
        if producer is None:
            producer = self.context.topic_connections.create_producer(
                self.agent_id, {"topic": topic}
            )
            await producer.start()
            self._producers[topic] = producer
        return producer

    async def process_record(self, record: Record) -> List[Record]:
        el_ctx = TransformContext(record).el_context()
        for condition, destination, action in self.routes:
            if condition is None or bool(condition.evaluate(el_ctx)):
                if action == "drop":
                    return []
                if destination:
                    producer = await self._producer(destination)
                    await producer.write(record)
                    return []
        return [record]


class TimerSourceAgent(AgentSource):
    """Emit a record every ``period-seconds`` with computed fields."""

    agent_type = "timer-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.period = float(configuration.get("period-seconds", 60))
        self.fields = [
            (field["name"], Expression(str(field["expression"])))
            for field in configuration.get("fields", [])
        ]
        self._next_fire = time.monotonic() + self.period

    async def read(self, max_records: int = 100) -> List[Record]:
        delay = self._next_fire - time.monotonic()
        if delay > 0:
            await asyncio.sleep(min(delay, 0.2))
            if time.monotonic() < self._next_fire:
                return []
        self._next_fire = time.monotonic() + self.period
        value: Dict[str, Any] = {}
        el_ctx = {"value": {}, "key": None, "properties": {}, "timestamp": now_millis()}
        for name, expression in self.fields:
            target = name.split(".", 1)[1] if name.startswith("value.") else name
            value[target] = expression.evaluate(el_ctx)
        return [Record(value=value, timestamp=now_millis())]


class TriggerEventAgent(SingleRecordProcessor):
    """Emit a side event to a topic when a condition holds
    (``trigger-event`` agent)."""

    agent_type = "trigger-event"

    async def init(self, configuration: Dict[str, Any]) -> None:
        when = configuration.get("when")
        self.when = Expression(when) if when else None
        self.destination = configuration.get("destination")
        self.continue_processing = bool(configuration.get("continue-processing", True))
        self.fields = [
            (f["name"], Expression(str(f["expression"])))
            for f in configuration.get("fields", [])
        ]
        self._producer = None

    async def close(self) -> None:
        if self._producer is not None:
            await self._producer.close()

    async def process_record(self, record: Record) -> List[Record]:
        el_ctx = TransformContext(record).el_context()
        if self.when is None or bool(self.when.evaluate(el_ctx)):
            event_value: Dict[str, Any] = {}
            for name, expression in self.fields:
                target = name.split(".", 1)[1] if name.startswith("value.") else name
                event_value[target] = expression.evaluate(el_ctx)
            if self.destination:
                if self._producer is None:
                    self._producer = self.context.topic_connections.create_producer(
                        self.agent_id, {"topic": self.destination}
                    )
                    await self._producer.start()
                await self._producer.write(
                    Record(value=event_value or record.value, key=record.key)
                )
            if not self.continue_processing:
                return []
        return [record]


class LogEventAgent(SingleRecordProcessor):
    """Structured-log records as they pass (``log-event`` agent)."""

    agent_type = "log-event"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.prefix = configuration.get("message", "log-event")
        self.fields = [
            (f["name"], Expression(str(f["expression"])))
            for f in configuration.get("fields", [])
        ]

    async def process_record(self, record: Record) -> List[Record]:
        el_ctx = TransformContext(record).el_context()
        if self.fields:
            payload = {name: expr.evaluate(el_ctx) for name, expr in self.fields}
        else:
            payload = {"value": record.value, "key": record.key}
        logger.info("%s %s", self.prefix, payload)
        return [record]
