"""Kafka Connect adapters: run any Connect connector as an agent.

Reference: ``langstream-kafka-runtime/src/main/java/ai/langstream/kafka/
extensions/kafkaconnect/{KafkaConnectSourceAgent.java:67,
KafkaConnectSinkAgent.java:65}`` — the reference embeds connector jars
in-process. The TPU build is Python, so it drives a **Connect worker**
through its REST API instead (the deployment shape Connect itself
recommends): the agent creates/updates the connector on start, deletes
it on close (optional), and the records ride Kafka topics that this
framework's own Kafka runtime reads/writes.

- ``kafka-connect-source``: Connect source connector → its output topic
  → records into the pipeline.
- ``kafka-connect-sink``: pipeline records → a staging topic → Connect
  sink connector consuming it. ``handles_commit`` stays False: the
  staging write is the durability point for the pipeline (the connector
  tracks its own consumer offsets from there).

Config (both): ``connect-url``, ``connector-name``, ``connector-config``
(the raw Connect config dict), ``bootstrapServers`` (for the data
topics), ``topic`` (output/staging topic), ``delete-on-close`` (default
false).

Deployment: point ``connect-url`` at an existing Connect cluster, or
enable the bundled distributed-mode worker the helm chart ships
(``kafkaConnect.enabled=true`` → ``http://<release>-connect:8083``).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentSink, AgentSource
from langstream_tpu.api.records import Record
from langstream_tpu.api.topics import OffsetPosition

logger = logging.getLogger(__name__)


class _ConnectRestClient:
    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def ensure_connector(
        self, name: str, config: Dict[str, Any]
    ) -> None:
        """Create-or-update (PUT /connectors/{name}/config is idempotent)."""
        session = await self._get_session()
        async with session.put(
            f"{self.url}/connectors/{name}/config", json=config
        ) as response:
            if response.status >= 300:
                body = await response.text()
                raise IOError(
                    f"connect PUT {name}: HTTP {response.status}: {body[:400]}"
                )

    async def status(self, name: str) -> Dict[str, Any]:
        session = await self._get_session()
        async with session.get(
            f"{self.url}/connectors/{name}/status"
        ) as response:
            if response.status >= 300:
                return {"connector": {"state": f"HTTP {response.status}"}}
            return await response.json(content_type=None)

    async def delete_connector(self, name: str) -> None:
        session = await self._get_session()
        async with session.delete(
            f"{self.url}/connectors/{name}"
        ) as response:
            if response.status not in (204, 404, 200):
                body = await response.text()
                raise IOError(
                    f"connect DELETE {name}: HTTP {response.status}: "
                    f"{body[:200]}"
                )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class _ConnectAgentBase:
    async def init(self, configuration: Dict[str, Any]) -> None:
        self.connect_url = configuration["connect-url"]
        self.connector_name = configuration["connector-name"]
        self.connector_config = dict(
            configuration.get("connector-config") or {}
        )
        self.data_topic = configuration["topic"]
        self.bootstrap = (
            configuration.get("bootstrapServers")
            or configuration.get("bootstrap-servers")
            or "127.0.0.1:9092"
        )
        self.delete_on_close = bool(configuration.get("delete-on-close"))
        self.rest = _ConnectRestClient(self.connect_url)
        from langstream_tpu.topics.kafka.runtime import (
            KafkaTopicConnectionsRuntime,
        )

        self._runtime = KafkaTopicConnectionsRuntime(
            {"bootstrapServers": self.bootstrap}
        )

    async def _teardown(self) -> None:
        if self.delete_on_close:
            try:
                await self.rest.delete_connector(self.connector_name)
            except Exception:  # noqa: BLE001 — best effort on shutdown
                logger.exception(
                    "failed deleting connector %s", self.connector_name
                )
        await self.rest.close()
        await self._runtime.close()


class KafkaConnectSourceAgent(_ConnectAgentBase, AgentSource):
    """Connect source connector → Kafka topic → pipeline records."""

    agent_type = "kafka-connect-source"

    async def start(self) -> None:
        self.connector_config.setdefault("name", self.connector_name)
        await self.rest.ensure_connector(
            self.connector_name, self.connector_config
        )
        status = await self.rest.status(self.connector_name)
        logger.info(
            "connector %s: %s", self.connector_name,
            status.get("connector", {}).get("state"),
        )
        group = f"langstream-{self.agent_id or self.connector_name}"
        self._consumer = self._runtime.create_consumer(
            self.agent_id or "kafka-connect",
            {"topic": self.data_topic, "group": group},
        )
        await self._consumer.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        return await self._consumer.read(
            max_records=max_records, timeout=0.2
        )

    async def commit(self, records: List[Record]) -> None:
        await self._consumer.commit(records)

    async def close(self) -> None:
        await self._consumer.close()
        await self._teardown()


class KafkaConnectSinkAgent(_ConnectAgentBase, AgentSink):
    """Pipeline records → staging Kafka topic → Connect sink connector."""

    agent_type = "kafka-connect-sink"

    async def start(self) -> None:
        self.connector_config.setdefault("name", self.connector_name)
        self.connector_config.setdefault("topics", self.data_topic)
        await self.rest.ensure_connector(
            self.connector_name, self.connector_config
        )
        self._producer = self._runtime.create_producer(
            self.agent_id or "kafka-connect", {"topic": self.data_topic}
        )
        await self._producer.start()

    async def write(self, record: Record) -> None:
        await self._producer.write(record)

    async def close(self) -> None:
        await self._producer.close()
        await self._teardown()
