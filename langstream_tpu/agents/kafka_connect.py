"""Kafka Connect adapters: run any Connect connector as an agent.

Reference: ``langstream-kafka-runtime/src/main/java/ai/langstream/kafka/
extensions/kafkaconnect/{KafkaConnectSourceAgent.java:67,
KafkaConnectSinkAgent.java:65}`` — the reference embeds connector jars
in-process. The TPU build is Python, so it drives a **Connect worker**
through its REST API instead (the deployment shape Connect itself
recommends): the agent creates/updates the connector on start, deletes
it on close (optional), and the records ride Kafka topics that this
framework's own Kafka runtime reads/writes.

- ``kafka-connect-source``: Connect source connector → its output topic
  → records into the pipeline.
- ``kafka-connect-sink``: pipeline records → a staging topic → Connect
  sink connector consuming it. ``handles_commit`` stays False: the
  staging write is the durability point for the pipeline (the connector
  tracks its own consumer offsets from there).

Config (both): ``connect-url``, ``connector-name``, ``connector-config``
(the raw Connect config dict), ``bootstrapServers`` (for the data
topics), ``topic`` (output/staging topic), ``delete-on-close`` (default
false).

Deployment: point ``connect-url`` at an existing Connect cluster, or
enable the bundled distributed-mode worker the helm chart ships
(``kafkaConnect.enabled=true`` → ``http://<release>-connect:8083``).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentSink, AgentSource
from langstream_tpu.api.records import Record
from langstream_tpu.api.topics import OffsetPosition

logger = logging.getLogger(__name__)


def _coerce_bool(value: Any, default: bool) -> bool:
    """Boolean coercion matching the validation layer (docs.py accepts
    "true"/"false"/"1"/"0" strings): bool("false") is True, so plain
    bool() would silently ignore a string opt-out from a placeholder."""
    if value is None or value == "":
        return default
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        return value.strip().lower() in ("true", "1", "yes")
    return bool(value)


class _ConnectRestClient:
    """REST client for a Connect distributed worker.

    A distributed worker answers **409** on config-mutating (and some
    read) endpoints while a group rebalance is in flight — transient by
    definition — so every call here retries 409s with backoff until
    ``rebalance_timeout`` instead of failing the agent for a condition
    the worker resolves by itself."""

    def __init__(self, url: str, rebalance_timeout: float = 30.0) -> None:
        self.url = url.rstrip("/")
        self.rebalance_timeout = rebalance_timeout
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _request(
        self, method: str, path: str,
        retry_budget: Optional[float] = None, **kwargs,
    ):
        """One request with 409-rebalance retry; returns (status, text).
        ``retry_budget`` overrides the default rebalance_timeout — pass
        0 for a single attempt (hot-path health probes must not stall
        behind a rebalance window)."""
        import asyncio
        import time

        session = await self._get_session()
        budget = (
            self.rebalance_timeout if retry_budget is None else retry_budget
        )
        deadline = time.monotonic() + budget
        delay = 0.2
        while True:
            async with session.request(
                method, f"{self.url}{path}", **kwargs
            ) as response:
                body = await response.text()
                if response.status != 409 or time.monotonic() >= deadline:
                    return response.status, body
            logger.info(
                "connect %s %s: 409 (rebalance in progress), retrying",
                method, path,
            )
            await asyncio.sleep(delay)
            delay = min(delay * 2, 2.0)

    async def ensure_connector(
        self, name: str, config: Dict[str, Any]
    ) -> None:
        """Create-or-update (PUT /connectors/{name}/config is idempotent)."""
        status, body = await self._request(
            "PUT", f"/connectors/{name}/config", json=config
        )
        if status >= 300:
            raise IOError(f"connect PUT {name}: HTTP {status}: {body[:400]}")

    async def status(
        self, name: str, retry_budget: Optional[float] = None
    ) -> Dict[str, Any]:
        import json as _json

        status, body = await self._request(
            "GET", f"/connectors/{name}/status", retry_budget=retry_budget
        )
        if status >= 300:
            return {"connector": {"state": f"HTTP {status}"}}
        return _json.loads(body)

    async def restart_task(self, name: str, task_id: int) -> None:
        status, body = await self._request(
            "POST", f"/connectors/{name}/tasks/{task_id}/restart"
        )
        if status >= 300:
            raise IOError(
                f"connect restart {name}/{task_id}: HTTP {status}: "
                f"{body[:200]}"
            )

    async def delete_connector(self, name: str) -> None:
        status, body = await self._request("DELETE", f"/connectors/{name}")
        if status not in (204, 404, 200):
            raise IOError(
                f"connect DELETE {name}: HTTP {status}: {body[:200]}"
            )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class _ConnectAgentBase:
    async def init(self, configuration: Dict[str, Any]) -> None:
        self.connect_url = configuration["connect-url"]
        self.connector_name = configuration["connector-name"]
        self.connector_config = dict(
            configuration.get("connector-config") or {}
        )
        self.data_topic = configuration["topic"]
        self.bootstrap = (
            configuration.get("bootstrapServers")
            or configuration.get("bootstrap-servers")
            or "127.0.0.1:9092"
        )
        self.delete_on_close = _coerce_bool(
            configuration.get("delete-on-close"), False
        )
        self.rest = _ConnectRestClient(
            self.connect_url,
            rebalance_timeout=float(
                configuration.get("rebalance-timeout", 30)
            ),
        )
        # a FAILED task on the worker stalls data flow silently from the
        # pipeline's point of view (records just stop) — poll status and
        # restart failed tasks, the remediation the Connect REST API
        # exists for. 0 disables.
        self.restart_failed = _coerce_bool(
            configuration.get("restart-failed-tasks"), True
        )
        self.health_interval = float(
            configuration.get("health-check-interval", 30)
        )
        self._last_health = 0.0
        from langstream_tpu.topics.kafka.runtime import (
            KafkaTopicConnectionsRuntime,
        )

        self._runtime = KafkaTopicConnectionsRuntime(
            {"bootstrapServers": self.bootstrap}
        )

    async def _ensure_data_topic(self) -> None:
        """The data/staging topic is agent config, not a declared
        pipeline topic, so the planner never creates it — and a cluster
        without auto-create then fails every write with
        UNKNOWN_TOPIC_OR_PARTITION (found by live drive). Create-if-not-
        exists via the admin API (already-exists is tolerated)."""
        from langstream_tpu.api.topics import TopicSpec

        admin = self._runtime.create_admin()
        try:
            await admin.create_topic(TopicSpec(name=self.data_topic))
        except Exception as error:  # noqa: BLE001 — e.g. no ACL: the
            # subsequent produce/consume gives the real error if the
            # topic truly doesn't exist
            logger.warning(
                "could not ensure data topic %s: %r", self.data_topic, error
            )

    async def check_health(self, force: bool = False) -> None:
        """Poll connector status (rate-limited to ``health-check-interval``)
        and restart FAILED tasks. Called from the data path, so a worker
        outage degrades to a log line rather than killing the agent."""
        import time

        if self.health_interval <= 0:
            return
        now = time.monotonic()
        if not force and now - self._last_health < self.health_interval:
            return
        self._last_health = now
        try:
            # retry_budget=0: health rides the data path — a routine
            # rebalance 409 must cost one round trip, not stall records
            # for the whole rebalance_timeout
            status = await self.rest.status(
                self.connector_name, retry_budget=0
            )
        except Exception as error:  # noqa: BLE001 — health is best-effort
            logger.warning(
                "connector %s status check failed: %r",
                self.connector_name, error,
            )
            return
        for task in status.get("tasks", []):
            if task.get("state") == "FAILED":
                trace = (task.get("trace") or "")[:400]
                logger.warning(
                    "connector %s task %s FAILED on %s: %s",
                    self.connector_name, task.get("id"),
                    task.get("worker_id"), trace,
                )
                if self.restart_failed:
                    try:
                        await self.rest.restart_task(
                            self.connector_name, int(task["id"])
                        )
                        logger.info(
                            "restarted task %s of %s",
                            task["id"], self.connector_name,
                        )
                    except Exception as error:  # noqa: BLE001
                        logger.warning(
                            "task restart failed for %s/%s: %r",
                            self.connector_name, task.get("id"), error,
                        )

    async def _teardown(self) -> None:
        if self.delete_on_close:
            try:
                await self.rest.delete_connector(self.connector_name)
            except Exception:  # noqa: BLE001 — best effort on shutdown
                logger.exception(
                    "failed deleting connector %s", self.connector_name
                )
        await self.rest.close()
        await self._runtime.close()


class KafkaConnectSourceAgent(_ConnectAgentBase, AgentSource):
    """Connect source connector → Kafka topic → pipeline records."""

    agent_type = "kafka-connect-source"

    async def start(self) -> None:
        self.connector_config.setdefault("name", self.connector_name)
        await self.rest.ensure_connector(
            self.connector_name, self.connector_config
        )
        status = await self.rest.status(self.connector_name)
        logger.info(
            "connector %s: %s", self.connector_name,
            status.get("connector", {}).get("state"),
        )
        await self._ensure_data_topic()
        group = f"langstream-{self.agent_id or self.connector_name}"
        self._consumer = self._runtime.create_consumer(
            self.agent_id or "kafka-connect",
            {"topic": self.data_topic, "group": group},
        )
        await self._consumer.start()

    async def read(self, max_records: int = 100) -> List[Record]:
        await self.check_health()
        return await self._consumer.read(
            max_records=max_records, timeout=0.2
        )

    async def commit(self, records: List[Record]) -> None:
        await self._consumer.commit(records)

    async def close(self) -> None:
        await self._consumer.close()
        await self._teardown()


class KafkaConnectSinkAgent(_ConnectAgentBase, AgentSink):
    """Pipeline records → staging Kafka topic → Connect sink connector."""

    agent_type = "kafka-connect-sink"

    async def start(self) -> None:
        self.connector_config.setdefault("name", self.connector_name)
        self.connector_config.setdefault("topics", self.data_topic)
        await self.rest.ensure_connector(
            self.connector_name, self.connector_config
        )
        await self._ensure_data_topic()
        self._producer = self._runtime.create_producer(
            self.agent_id or "kafka-connect", {"topic": self.data_topic}
        )
        await self._producer.start()

    async def write(self, record: Record) -> None:
        await self.check_health()
        await self._producer.write(record)

    async def close(self) -> None:
        await self._producer.close()
        await self._teardown()
