"""User-custom Python agents: in-process by default, crash-isolated on
request.

The reference runs user Python code in a subprocess bridged over localhost
gRPC (``langstream-agent-grpc/src/main/proto/langstream_grpc/proto/agent.proto:24-111``,
``PythonGrpcServer.java:31``) because its runtime is a JVM. This framework's
runtime *is* Python, so user agents load in-process by default: the
``className`` config names a ``module.Class`` importable from the
application's ``python/`` directory (added to ``sys.path`` by the planner,
mirroring the reference's PYTHONPATH contract,
``PythonGrpcServer.java:54-91``). Set ``isolation: process`` (or env
``LS_PYTHON_ISOLATION=process`` to flip the default) to restore the
reference's crash boundary for untrusted code — the agent then runs in a
child process behind the socket contract in ``agents/isolation.py``.

User classes follow the same duck-typed shape as the reference Python SDK
(``langstream-runtime/langstream-runtime-impl/src/main/python/langstream_grpc/api.py:34-195``):

- processor: ``process(record) -> list`` (async or sync) — each result is
  coerced via :func:`~langstream_tpu.api.records.record_from_value`.
- source: ``read() -> list``; optional ``commit(records)``.
- sink: ``write(record)``.
- service: ``main()`` / ``join()``.
- all kinds: optional ``init(config)``, ``start()``, ``close()``,
  ``set_context(context)``.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import sys
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import (
    AgentContext,
    AgentService,
    AgentSink,
    AgentSource,
    SingleRecordProcessor,
)
from langstream_tpu.api.records import Record, record_from_value
from langstream_tpu.runtime.registry import load_class


async def _maybe_await(value):
    if inspect.isawaitable(value):
        return await value
    return value


def _load_user_class(class_name: str, python_paths) -> type:
    """Resolve ``module.Class`` from the app's python dirs.

    When ``pythonPath`` entries are given, the user modules import under
    a synthetic package namespaced by the path set (same isolation the
    plugin system uses): two apps in one process may both ship a module
    named ``my_agent`` without the first import shadowing the second —
    a plain ``sys.path`` + ``import_module`` would cache the first one
    process-wide in ``sys.modules``. Without pythonPath, fall back to
    the plain import (framework-provided classes on sys.path).
    """
    if not python_paths:
        return load_class(class_name)
    import hashlib
    import importlib
    import types

    tag = hashlib.sha256(
        "\x00".join(sorted(str(p) for p in python_paths)).encode()
    ).hexdigest()[:12]
    namespace = "_ls_apps"
    package_name = f"{namespace}.app_{tag}"
    root = sys.modules.get(namespace)
    if root is None:
        root = types.ModuleType(namespace)
        root.__path__ = []  # type: ignore[attr-defined]
        sys.modules[namespace] = root
    package = sys.modules.get(package_name)
    if package is None:
        package = types.ModuleType(package_name)
        package.__path__ = [str(p) for p in python_paths]  # type: ignore[attr-defined]
        package.__package__ = package_name
        sys.modules[package_name] = package
    module_name, _, cls_name = class_name.rpartition(".")
    if not module_name:
        raise ValueError(
            f"className must be 'module.Class', got {class_name!r}"
        )
    module = importlib.import_module(f"{package_name}.{module_name}")
    return getattr(module, cls_name)


class _PythonAgentMixin:
    user_agent: Any = None

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.configuration = configuration
        class_name = configuration.get("className")
        if not class_name:
            raise ValueError("python agent requires 'className' configuration")
        isolation = configuration.get(
            "isolation", os.environ.get("LS_PYTHON_ISOLATION", "auto")
        )
        if isolation not in ("auto", "none", "process", "", None):
            # a typo ('Process', 'true') must not silently run untrusted
            # code in-process — the boundary the operator asked for
            # would be absent with no signal
            raise ValueError(
                f"python agent isolation must be 'auto', 'none', or "
                f"'process', got {isolation!r}"
            )
        if isolation == "auto":
            # apps that ship third-party deps in python/lib need the
            # reference's flat PYTHONPATH semantics — one interpreter
            # per app, i.e. the process boundary. Pure-app-code agents
            # stay in-process (namespaced imports keep them collision
            # -proof across apps).
            isolation = "process" if any(
                os.path.basename(str(p).rstrip("/")) == "lib"
                for p in configuration.get("pythonPath") or []
            ) else "none"
        if isolation == "process":
            # the reference's crash boundary (PythonGrpcServer.java:54-91):
            # untrusted user code runs in a child; a crash kills the pod,
            # not the runtime/engine. See agents/isolation.py.
            from langstream_tpu.agents.isolation import RemoteUserAgent

            self.user_agent = await RemoteUserAgent.spawn(
                getattr(self, "agent_type", "python-agent"), configuration
            )
            return
        extra_path = configuration.get("pythonPath") or []
        cls = _load_user_class(class_name, extra_path)
        self.user_agent = cls()
        if hasattr(self.user_agent, "init"):
            await _maybe_await(self.user_agent.init(configuration))

    async def set_context(self, context: AgentContext) -> None:
        self.context = context
        if self.user_agent is not None and hasattr(self.user_agent, "set_context"):
            await _maybe_await(self.user_agent.set_context(context))

    async def start(self) -> None:
        if self.user_agent is not None and hasattr(self.user_agent, "start"):
            await _maybe_await(self.user_agent.start())

    async def close(self) -> None:
        if self.user_agent is not None and hasattr(self.user_agent, "close"):
            await _maybe_await(self.user_agent.close())

    def agent_info(self) -> Dict[str, Any]:
        info = super().agent_info()  # type: ignore[misc]
        info["className"] = getattr(self, "configuration", {}).get("className")
        if self.user_agent is not None and hasattr(self.user_agent, "agent_info"):
            info["user"] = self.user_agent.agent_info()
        return info


class PythonProcessorAgent(_PythonAgentMixin, SingleRecordProcessor):
    agent_type = "python-processor"

    async def process_record(self, record: Record) -> List[Record]:
        results = await _maybe_await(self.user_agent.process(record))
        if results is None:
            return []
        return [record_from_value(r, origin=record.origin) for r in results]


class PythonSourceAgent(_PythonAgentMixin, AgentSource):
    agent_type = "python-source"

    async def read(self, max_records: int = 100) -> List[Record]:
        results = await _maybe_await(self.user_agent.read())
        if not results:
            # politeness: avoid a hot spin when the user source is empty
            await asyncio.sleep(0.05)
            return []
        return [record_from_value(r) for r in results]

    async def commit(self, records: List[Record]) -> None:
        if hasattr(self.user_agent, "commit"):
            await _maybe_await(self.user_agent.commit(records))

    async def permanent_failure(self, record: Record, error: BaseException) -> None:
        if hasattr(self.user_agent, "permanent_failure"):
            await _maybe_await(self.user_agent.permanent_failure(record, error))
        else:
            raise error


class PythonSinkAgent(_PythonAgentMixin, AgentSink):
    agent_type = "python-sink"

    async def write(self, record: Record) -> None:
        await _maybe_await(self.user_agent.write(record))


class PythonServiceAgent(_PythonAgentMixin, AgentService):
    agent_type = "python-service"

    async def join(self) -> None:
        if hasattr(self.user_agent, "join"):
            await _maybe_await(self.user_agent.join())
        elif hasattr(self.user_agent, "main"):
            await _maybe_await(self.user_agent.main())
        else:
            await asyncio.Event().wait()
