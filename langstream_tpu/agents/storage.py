"""Object-store sources: S3 (native SigV4 REST client), local files, Azure.

Equivalent of the reference's storage sources
(``langstream-agents/langstream-agent-s3/.../S3Source.java:51`` and
``langstream-agent-azure-blob-storage-source/.../AzureBlobStorageSource.java:39``):
list objects in a bucket, emit one record per object, optionally delete
after downstream processing commits (``delete-objects``).

The S3 client here is a minimal aiohttp+SigV4 implementation (no boto3 in
this image) that works against AWS S3 and MinIO; Azure rides the native
REST client in ``agents/azure_blob.py`` (Shared Key or SAS auth, no
Azure SDK). ``file-source`` reads a local directory — the zero-infra
analogue used by tests and local runs.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import os
import urllib.parse
from typing import Any, Dict, List, Optional
from xml.etree import ElementTree

from langstream_tpu.api.agent import AgentSource
from langstream_tpu.api.records import Record


# ---------------------------------------------------------------------- #
# minimal SigV4 S3 client
# ---------------------------------------------------------------------- #
class S3Client:
    def __init__(
        self,
        *,
        endpoint: str,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
    ) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _sign(self, method: str, path: str, query: str, headers: Dict[str, str],
              payload_hash: str) -> Dict[str, str]:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date_stamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.endpoint).netloc
        headers = {**headers, "host": host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_hash}
        signed_names = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{name}:{headers[name].strip()}\n" for name in sorted(headers)
        )
        canonical_request = "\n".join(
            [method, path, query, canonical_headers, signed_names, payload_hash]
        )
        scope = f"{date_stamp}/{self.region}/s3/aws4_request"
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical_request.encode()).hexdigest(),
            ]
        )

        def _hmac(key: bytes, message: str) -> bytes:
            return hmac.new(key, message.encode(), hashlib.sha256).digest()

        key = _hmac(f"AWS4{self.secret_key}".encode(), date_stamp)
        key = _hmac(key, self.region)
        key = _hmac(key, "s3")
        key = _hmac(key, "aws4_request")
        signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_names}, Signature={signature}"
        )
        return headers

    async def _request(self, method: str, path: str, query: Dict[str, str],
                       body: bytes = b"") -> bytes:
        session = await self._get_session()
        payload_hash = hashlib.sha256(body).hexdigest()
        query_string = "&".join(
            f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
            for k, v in sorted(query.items())
        )
        headers = self._sign(method, path, query_string, {}, payload_hash)
        url = f"{self.endpoint}{path}" + (f"?{query_string}" if query_string else "")
        async with session.request(method, url, data=body, headers=headers) as resp:
            payload = await resp.read()
            if resp.status >= 300:
                raise IOError(f"S3 {method} {path}: HTTP {resp.status}: {payload[:500]!r}")
            return payload

    async def list_objects(self, bucket: str, prefix: str = "") -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        token: Optional[str] = None
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if token:
                query["continuation-token"] = token
            payload = await self._request("GET", f"/{bucket}", query)
            root = ElementTree.fromstring(payload)
            ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
            for contents in root.findall(f"{ns}Contents"):
                out.append(
                    {
                        "key": contents.findtext(f"{ns}Key"),
                        "size": int(contents.findtext(f"{ns}Size") or 0),
                        "etag": (contents.findtext(f"{ns}ETag") or "").strip('"'),
                    }
                )
            if root.findtext(f"{ns}IsTruncated") != "true":
                return out
            token = root.findtext(f"{ns}NextContinuationToken")
            if not token:
                return out

    async def get_object(self, bucket: str, key: str) -> bytes:
        return await self._request("GET", f"/{bucket}/{urllib.parse.quote(key)}", {})

    async def put_object(self, bucket: str, key: str, body: bytes) -> None:
        await self._request("PUT", f"/{bucket}/{urllib.parse.quote(key)}", {}, body)

    async def delete_object(self, bucket: str, key: str) -> None:
        await self._request("DELETE", f"/{bucket}/{urllib.parse.quote(key)}", {})


class S3Source(AgentSource):
    """Emit one record per S3 object; delete on commit when configured
    (``S3Source.java:51`` semantics: idle-poll the bucket, remember
    processed keys, ``delete-objects`` after commit)."""

    agent_type = "s3-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.bucket = configuration.get("bucketName", "langstream-source")
        self.client = S3Client(
            endpoint=configuration.get("endpoint", "https://s3.amazonaws.com"),
            access_key=configuration.get("access-key", ""),
            secret_key=configuration.get("secret-key", ""),
            region=configuration.get("region", "us-east-1"),
        )
        self.delete_after = bool(configuration.get("delete-objects", True))
        self.idle_time = float(configuration.get("idle-time", 5))
        self.extensions = [
            e.strip() for e in str(configuration.get("file-extensions", "")).split(",")
            if e.strip()
        ]
        self._processed: set = set()

    async def read(self, max_records: int = 100) -> List[Record]:
        objects = await self.client.list_objects(self.bucket)
        out: List[Record] = []
        for obj in objects:
            key = obj["key"]
            if key in self._processed:
                continue
            if self.extensions and not any(key.endswith(f".{e}") for e in self.extensions):
                continue
            body = await self.client.get_object(self.bucket, key)
            self._processed.add(key)
            out.append(Record(value=body, key=key, headers=(("name", key),)))
            if len(out) >= max_records:
                break
        if not out:
            await asyncio.sleep(self.idle_time)
        return out

    async def commit(self, records: List[Record]) -> None:
        if not self.delete_after:
            return
        for record in records:
            if record.key:
                await self.client.delete_object(self.bucket, str(record.key))

    async def close(self) -> None:
        await self.client.close()


class FileSource(AgentSource):
    """Local-directory source (the zero-infra S3Source analogue)."""

    agent_type = "file-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.path = configuration["path"]
        self.delete_after = bool(configuration.get("delete-objects", False))
        self.idle_time = float(configuration.get("idle-time", 1))
        self.extensions = [
            e.strip() for e in str(configuration.get("file-extensions", "")).split(",")
            if e.strip()
        ]
        self._processed: set = set()

    async def read(self, max_records: int = 100) -> List[Record]:
        out: List[Record] = []
        try:
            names = sorted(os.listdir(self.path))
        except FileNotFoundError:
            names = []
        for name in names:
            full = os.path.join(self.path, name)
            if full in self._processed or not os.path.isfile(full):
                continue
            if self.extensions and not any(name.endswith(f".{e}") for e in self.extensions):
                continue
            with open(full, "rb") as handle:
                body = handle.read()
            self._processed.add(full)
            out.append(Record(value=body, key=name, headers=(("name", name),)))
            if len(out) >= max_records:
                break
        if not out:
            await asyncio.sleep(self.idle_time)
        return out

    async def commit(self, records: List[Record]) -> None:
        if not self.delete_after:
            return
        for record in records:
            full = os.path.join(self.path, str(record.key))
            if os.path.exists(full):
                os.unlink(full)


class AzureBlobStorageSource(AgentSource):
    """Emit one record per blob; delete on commit when configured
    (reference: ``AzureBlobStorageSource.java:39`` — same polling shape
    as :class:`S3Source`, over the native Azure REST client)."""

    agent_type = "azure-blob-storage-source"

    async def init(self, configuration: Dict[str, Any]) -> None:
        from langstream_tpu.agents.azure_blob import (
            AzureBlobClient,
            parse_connection_string,
        )

        endpoint = configuration.get("endpoint")
        account = configuration.get("storage-account-name")
        account_key = configuration.get("storage-account-key")
        connection = configuration.get("storage-account-connection-string")
        if connection:
            parsed = parse_connection_string(connection)
            endpoint = endpoint or parsed.get("endpoint")
            account = account or parsed.get("account")
            account_key = account_key or parsed.get("key")
        if not endpoint:
            if not account:
                raise ValueError(
                    "azure-blob-storage-source needs 'endpoint', "
                    "'storage-account-name', or a connection string"
                )
            endpoint = f"https://{account}.blob.core.windows.net"
        self.client = AzureBlobClient(
            endpoint=endpoint,
            container=configuration.get(
                "container", "langstream-azure-source"
            ),
            account=account,
            account_key=account_key,
            sas_token=configuration.get("sas-token"),
        )
        self.delete_after = bool(configuration.get("delete-objects", True))
        self.idle_time = float(configuration.get("idle-time", 5))
        self.extensions = [
            e.strip()
            for e in str(configuration.get("file-extensions", "")).split(",")
            if e.strip()
        ]
        self._processed: set = set()

    async def read(self, max_records: int = 100) -> List[Record]:
        blobs = await self.client.list_blobs()
        out: List[Record] = []
        for blob in blobs:
            name = blob["name"]
            if name in self._processed:
                continue
            if self.extensions and not any(
                name.endswith(f".{e}") for e in self.extensions
            ):
                continue
            body = await self.client.get_blob(name)
            self._processed.add(name)
            out.append(Record(value=body, key=name, headers=(("name", name),)))
            if len(out) >= max_records:
                break
        if not out:
            await asyncio.sleep(self.idle_time)
        return out

    async def commit(self, records: List[Record]) -> None:
        if not self.delete_after:
            return
        for record in records:
            if record.key:
                await self.client.delete_blob(str(record.key))

    async def close(self) -> None:
        await self.client.close()
