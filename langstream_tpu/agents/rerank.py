"""Re-rank agent: MMR re-ranking of retrieved documents.

Equivalent of the reference's ``ReRankAgent``
(``langstream-agents/langstream-ai-agents/src/main/java/ai/langstream/agents/ai/rerank/ReRankAgent.java``):
re-orders a candidate list under a context budget using Maximal Marginal
Relevance over the query/document embeddings.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import SingleRecordProcessor
from langstream_tpu.api.records import Record
from langstream_tpu.agents.el import Expression
from langstream_tpu.agents.transform import TransformContext


def _cosine(a: List[float], b: List[float]) -> float:
    dot = sum(x * y for x, y in zip(a, b))
    norm_a = math.sqrt(sum(x * x for x in a)) or 1.0
    norm_b = math.sqrt(sum(y * y for y in b)) or 1.0
    return dot / (norm_a * norm_b)


def mmr_rank(
    query_vector: List[float],
    candidates: List[Dict[str, Any]],
    *,
    vector_field: str,
    lambda_param: float = 0.5,
    max_results: int = 10,
) -> List[Dict[str, Any]]:
    """Greedy MMR: balance relevance to the query against redundancy with
    already-selected documents."""
    remaining = [c for c in candidates if c.get(vector_field) is not None]
    selected: List[Dict[str, Any]] = []
    while remaining and len(selected) < max_results:
        best, best_score = None, -math.inf
        for candidate in remaining:
            relevance = _cosine(query_vector, candidate[vector_field])
            redundancy = max(
                (
                    _cosine(candidate[vector_field], chosen[vector_field])
                    for chosen in selected
                ),
                default=0.0,
            )
            score = lambda_param * relevance - (1 - lambda_param) * redundancy
            if score > best_score:
                best, best_score = candidate, score
        selected.append(best)
        remaining.remove(best)
    return selected


class ReRankAgent(SingleRecordProcessor):
    agent_type = "re-rank"

    async def init(self, configuration: Dict[str, Any]) -> None:
        self.field = configuration.get("field", "value.query-result")
        self.output_field = configuration.get("output-field", self.field)
        self.algorithm = configuration.get("algorithm", "MMR")
        self.lambda_param = float(configuration.get("lambda", 0.5))
        self.max_results = int(configuration.get("max", 10))
        self.query_embeddings = Expression(
            configuration.get("query-embeddings", "value.question_embeddings")
        )
        # name of the embedding field INSIDE each candidate dict
        self.vector_field = configuration.get("vector-field", "vector")

    async def process_record(self, record: Record) -> List[Record]:
        ctx = TransformContext(record)
        el_ctx = ctx.el_context()
        candidates = ctx.get_field(self.field) or []
        query_vector = self.query_embeddings.evaluate(el_ctx)
        if self.algorithm.upper() != "MMR":
            raise ValueError(f"unknown re-rank algorithm {self.algorithm!r}")
        if query_vector is None:
            ranked = list(candidates)[: self.max_results]
        else:
            ranked = mmr_rank(
                list(query_vector),
                list(candidates),
                vector_field=self.vector_field,
                lambda_param=self.lambda_param,
                max_results=self.max_results,
            )
        ctx.set_field(self.output_field, ranked)
        return [ctx.to_record()]
