"""Datasource SPI + built-in implementations.

Parity with the reference's datasource layer
(``langstream-agents/langstream-ai-agents/.../datasource/impl/{AstraDataSource,JdbcDataSourceProvider}.java``):
``resources:`` entries of type ``datasource`` resolve to a queryable
service used by the ``query`` step and the vector agents.

Built-ins:

- ``service: sqlite``  — stdlib sqlite3 (the JDBC-equivalent relational
  path; supports query + execute with ``?`` params).
- ``service: memory``  — in-process table of dict rows with a tiny filter
  syntax, for tests and docs.
- ``service: vector``  — the TPU-native vector store
  (``langstream_tpu.agents.vectorstore``), queried with JSON specs.

External engines: OpenSearch/Elasticsearch, Pinecone, Solr, and Astra
(Data API) are implemented natively over their REST APIs
(``external_stores.py``); Cassandra CQL and Milvus gRPC (binary
protocols needing client libraries not in this image) are
declared-but-gated — configs validate and fail at ``start`` with an
explicit message rather than at plan time.
"""

from __future__ import annotations

import asyncio
import json
import re
from typing import Any, Dict, List, Optional

# engines whose client protocol needs a library not in this image
# (CQL; generic JDBC has no wire protocol at all); REST-based engines —
# OpenSearch, Pinecone, Solr, Astra, Milvus — are implemented natively
# in ``external_stores.py``
_GATED_SERVICES = {"cassandra", "jdbc"}


class DataSource:
    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        raise NotImplementedError

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        raise NotImplementedError

    async def close(self) -> None:
        ...


class SqliteDataSource(DataSource):
    """Relational datasource over stdlib sqlite3 (reference analogue:
    ``JdbcDataSourceProvider``)."""

    def __init__(self, config: Dict[str, Any]) -> None:
        import sqlite3

        path = config.get("path") or config.get("url", ":memory:")
        if path.startswith("sqlite:"):
            path = path[len("sqlite:"):] or ":memory:"
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = asyncio.Lock()

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        async with self._lock:
            cursor = self._conn.execute(query, params)
            return [dict(row) for row in cursor.fetchall()]

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        async with self._lock:
            cursor = self._conn.execute(statement, params)
            self._conn.commit()
            return {"rowcount": cursor.rowcount, "lastrowid": cursor.lastrowid}

    async def close(self) -> None:
        self._conn.close()


class MemoryDataSource(DataSource):
    """Dict-row tables; query syntax: JSON ``{"table": ..., "where":
    {field: value}, "limit": n}`` with ``?`` params substituting values."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.tables: Dict[str, List[Dict[str, Any]]] = {
            name: list(rows) for name, rows in (config.get("tables") or {}).items()
        }

    async def query(self, query: str, params: List[Any]) -> List[Dict[str, Any]]:
        spec = json.loads(_substitute(query, params))
        rows = self.tables.get(spec.get("table", ""), [])
        where = spec.get("where", {})
        out = [
            row
            for row in rows
            if all(row.get(field) == expected for field, expected in where.items())
        ]
        limit = spec.get("limit")
        return out[:limit] if limit else out

    async def execute(self, statement: str, params: List[Any]) -> Dict[str, Any]:
        spec = json.loads(_substitute(statement, params))
        table = self.tables.setdefault(spec.get("table", "default"), [])
        if "insert" in spec:
            table.append(spec["insert"])
            return {"rowcount": 1}
        if "delete-where" in spec:
            before = len(table)
            table[:] = [
                row
                for row in table
                if not all(row.get(f) == v for f, v in spec["delete-where"].items())
            ]
            return {"rowcount": before - len(table)}
        raise ValueError(f"unsupported memory statement: {spec}")


def _substitute(query: str, params: List[Any]) -> str:
    """Replace ``?`` placeholders with JSON-encoded params. A quoted
    ``"?"`` (as produced by building the query spec with json.dumps) is
    treated as a bare placeholder, so params keep their JSON types.
    With no params the query passes through untouched, so literal ``?``
    characters in zero-param specs are safe."""
    if not params:
        return query
    query = query.replace('"?"', "?")
    parts = query.split("?")
    if len(parts) - 1 != len(params):
        if len(parts) == 1:
            return query
        raise ValueError(
            f"query has {len(parts) - 1} placeholders but {len(params)} params"
        )
    out = [parts[0]]
    for param, tail in zip(params, parts[1:]):
        out.append(json.dumps(param, default=str))
        out.append(tail)
    return "".join(out)


class DataSourceRegistry:
    """Resolve datasource resources to live connections (cached)."""

    def __init__(self, resources: Optional[Dict[str, Dict[str, Any]]] = None):
        self.resources = resources or {}
        self._cache: Dict[str, DataSource] = {}

    def resolve(self, resource_name: str) -> DataSource:
        if resource_name in self._cache:
            return self._cache[resource_name]
        resource = self.resources.get(resource_name)
        if resource is None:
            raise ValueError(
                f"unknown datasource {resource_name!r}; declared: "
                f"{sorted(self.resources)}"
            )
        config = resource.get("configuration", resource)
        service = config.get("service", "sqlite")
        if service in ("sqlite", "jdbc-sqlite"):
            source: DataSource = SqliteDataSource(config)
        elif service in ("memory", "in-memory"):
            source = MemoryDataSource(config)
        elif service == "vector":
            from langstream_tpu.agents.vectorstore import VectorStoreDataSource

            source = VectorStoreDataSource(config)
        elif service in ("opensearch", "elasticsearch"):
            from langstream_tpu.agents.external_stores import (
                OpenSearchDataSource,
            )

            source = OpenSearchDataSource(config)
        elif service == "pinecone":
            from langstream_tpu.agents.external_stores import (
                PineconeDataSource,
            )

            source = PineconeDataSource(config)
        elif service == "solr":
            from langstream_tpu.agents.external_stores import SolrDataSource

            source = SolrDataSource(config)
        elif service in ("astra", "astra-vector"):
            from langstream_tpu.agents.external_stores import AstraDataSource

            source = AstraDataSource(config)
        elif service == "milvus":
            from langstream_tpu.agents.external_stores import MilvusDataSource

            source = MilvusDataSource(config)
        elif service in _GATED_SERVICES:
            raise ValueError(
                f"datasource service {service!r} requires a client library "
                "not bundled in this build; use 'sqlite', 'memory', or "
                "'vector', or run against an external gateway"
            )
        else:
            raise ValueError(f"unknown datasource service {service!r}")
        self._cache[resource_name] = source
        return source

    async def close(self) -> None:
        for source in self._cache.values():
            await source.close()
        self._cache.clear()
