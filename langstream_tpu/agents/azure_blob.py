"""Azure Blob Storage over the REST API (no Azure SDK).

Reference: ``langstream-agent-azure-blob-storage-source/.../
AzureBlobStorageSource.java:39`` and the Azure ``CodeStorage`` impl.
Auth: either a SAS token (query-string credential) or Shared Key
(HMAC-SHA256 over the canonicalized request, the classic storage-account
key scheme) — both implemented directly, mirroring how ``agents/storage``
implements SigV4 for S3.
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import urllib.parse
from typing import Any, Dict, List, Optional
from xml.etree import ElementTree


class AzureBlobClient:
    def __init__(
        self,
        *,
        endpoint: str,
        container: str,
        account: Optional[str] = None,
        account_key: Optional[str] = None,
        sas_token: Optional[str] = None,
    ) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.container = container
        parsed = urllib.parse.urlparse(self.endpoint)
        if account:
            self.account = account
        elif parsed.path.strip("/"):
            # path-style endpoint (Azurite / emulators):
            # http://host:port/<account> — the account is the path
            self.account = parsed.path.strip("/").split("/")[0]
        else:
            self.account = parsed.netloc.split(".")[0]
        self.account_key = account_key
        self.sas_token = (sas_token or "").lstrip("?")
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- shared key signing --------------------------------------------- #
    def _sign(
        self, method: str, path: str, query: Dict[str, str],
        headers: Dict[str, str], content_length: int,
    ) -> Dict[str, str]:
        # RFC 1123 in C locale — strftime('%a/%b') is locale-dependent
        # and a localized day name breaks the Shared Key signature
        now = email.utils.formatdate(usegmt=True)
        headers = {
            **headers,
            "x-ms-date": now,
            "x-ms-version": "2021-08-06",
        }
        if not self.account_key:
            return headers
        canonical_headers = "".join(
            f"{name}:{headers[name]}\n"
            for name in sorted(h for h in headers if h.startswith("x-ms-"))
        )
        canonical_resource = f"/{self.account}{path}"
        for name in sorted(query):
            canonical_resource += f"\n{name}:{query[name]}"
        string_to_sign = "\n".join([
            method,
            "",                                     # Content-Encoding
            "",                                     # Content-Language
            str(content_length) if content_length else "",
            "",                                     # Content-MD5
            headers.get("content-type", ""),        # Content-Type
            "",                                     # Date (x-ms-date used)
            "", "", "", "", "",                     # If-*/Range
            canonical_headers + canonical_resource,
        ])
        key = base64.b64decode(self.account_key)
        signature = base64.b64encode(
            hmac.new(key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{signature}"
        return headers

    async def _request(
        self, method: str, blob: Optional[str],
        query: Optional[Dict[str, str]] = None,
        body: bytes = b"", headers: Optional[Dict[str, str]] = None,
    ) -> bytes:
        query = dict(query or {})
        path = f"/{self.container}"
        if blob:
            path += f"/{urllib.parse.quote(blob)}"
        signed = self._sign(
            method, path, query, dict(headers or {}), len(body)
        )
        query_string = urllib.parse.urlencode(query)
        if self.sas_token:
            query_string = (
                f"{query_string}&{self.sas_token}"
                if query_string else self.sas_token
            )
        url = f"{self.endpoint}{path}"
        if query_string:
            url += f"?{query_string}"
        session = await self._get_session()
        async with session.request(
            method, url, data=body or None, headers=signed
        ) as response:
            payload = await response.read()
            if response.status >= 300:
                raise IOError(
                    f"azure {method} {path}: HTTP {response.status}: "
                    f"{payload[:400]!r}"
                )
            return payload

    # -- blob ops ------------------------------------------------------- #
    async def list_blobs(self, prefix: str = "") -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list"}
            if prefix:
                query["prefix"] = prefix
            if marker:
                query["marker"] = marker
            payload = await self._request("GET", None, query)
            root = ElementTree.fromstring(payload)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name")
                size = blob.findtext("Properties/Content-Length") or "0"
                out.append({"name": name, "size": int(size)})
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return out

    async def get_blob(self, name: str) -> bytes:
        return await self._request("GET", name)

    async def put_blob(self, name: str, body: bytes) -> None:
        await self._request(
            "PUT", name, body=body,
            headers={"x-ms-blob-type": "BlockBlob",
                     "content-type": "application/octet-stream"},
        )

    async def delete_blob(self, name: str) -> None:
        await self._request("DELETE", name)


def parse_connection_string(connection: str) -> Dict[str, Optional[str]]:
    """Parse the standard ``AccountName=...;AccountKey=...;...`` form."""
    parts: Dict[str, str] = {}
    for piece in connection.split(";"):
        name, _, value = piece.partition("=")
        if name:
            parts[name.strip()] = value.strip()
    endpoint = parts.get("BlobEndpoint")
    account = parts.get("AccountName")
    if not endpoint and account:
        suffix = parts.get("EndpointSuffix", "core.windows.net")
        protocol = parts.get("DefaultEndpointsProtocol", "https")
        endpoint = f"{protocol}://{account}.blob.{suffix}"
    return {
        "endpoint": endpoint,
        "account": account,
        "key": parts.get("AccountKey"),
    }
