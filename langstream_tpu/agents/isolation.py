"""Process-isolated user Python agents: the crash boundary.

The reference ALWAYS runs user Python code in a child process behind a
bidi-gRPC contract with deliberate crash semantics
(``langstream-agent-grpc/src/main/java/ai/langstream/agents/grpc/PythonGrpcServer.java:54-91``
spawns ``python3 -m langstream_grpc`` on a free localhost port;
``langstream-runtime/langstream-runtime-impl/src/main/python/langstream_grpc/grpc_service.py:359``
``crash_process`` kills the child on unrecoverable agent error so the
pod — not the runtime — dies). This framework's runtime *is* Python, so
built-in agents run in-process; but **untrusted app code** still needs
the boundary: one bad native dependency or OOM in user code must not
destroy in-flight KV state for every session on the chip.

``isolation: process`` on a ``python-source/processor/sink/service``
agent restores that boundary the TPU-native way:

- the runner spawns ``sys.executable -m langstream_tpu.agents.isolation
  <socket>`` (a Unix domain socket; no ports, no TLS surface) and
  hands it the ``className``/``pythonPath``/configuration over the
  wire, NOT over argv (secrets stay out of /proc cmdline);
- the parent keeps the existing duck-typed user-agent surface — the
  proxy slots into :class:`~langstream_tpu.agents.python_agents._PythonAgentMixin`
  exactly where the in-process instance would sit, so all four agent
  kinds, the tuple/dict record coercions, and agent_info flow
  unchanged;
- **user exceptions** cross the boundary as structured errors and
  re-raise in the parent → the record-level error policies
  (fail/skip/dead-letter, ``api/errors.py``) apply exactly as
  in-process;
- **child death** (segfault, ``os._exit``, OOM-kill) surfaces as
  :class:`AgentProcessCrashed` on every in-flight and subsequent call →
  the runner's fail-fast path ends the pod, Kubernetes restarts it,
  and the serving engine in OTHER pods (and any engine living in this
  runner before the crash) is untouched — the reference's
  ``crash_process`` contract with the roles inverted.

Framing is length-prefixed JSON with base64 for byte values —
deliberately NOT pickle: nothing executable crosses the boundary in
either direction. The codec round-trips the JSON-shaped record domain
(str/num/bool/None/list/dict-with-string-keys) plus bytes and nested
Records; dicts whose keys collide with the escape markers are wrapped,
and non-string dict keys are stringified (a JSON limitation — same as
every broker codec in this framework).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import struct
import sys
import tempfile
import uuid
from typing import Any, Dict, List, Optional

from langstream_tpu.api.errors import FatalAgentError
from langstream_tpu.api.records import Record, record_from_value
from langstream_tpu.utils import wire_json

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
_MAX_FRAME = 256 * 1024 * 1024


class AgentProcessCrashed(FatalAgentError):
    """The isolated agent process died (crash, exit, or kill).

    Subclasses :class:`FatalAgentError` so the record error policy can
    NEVER consume it: with ``on-failure: skip`` a dead child would
    otherwise silently drop every subsequent record instead of
    restarting the pod (the reference's ``crash_process`` contract)."""


class RemoteAgentError(RuntimeError):
    """A user exception raised inside the isolated process, re-raised
    in the parent with the remote traceback attached."""

    def __init__(self, message: str, remote_traceback: str = "") -> None:
        super().__init__(message)
        self.remote_traceback = remote_traceback


# --------------------------------------------------------------------- #
# value / record codec (JSON + base64 bytes; bijective for the types the
# record model allows)
# --------------------------------------------------------------------- #
_RECORD_TAG = "__record__"
_RECORD_MARKERS = (frozenset((_RECORD_TAG,)),)


def _enc(value: Any) -> Any:
    return wire_json.encode_value(
        value,
        extra_markers=_RECORD_MARKERS,
        encode_special=lambda v: (
            {_RECORD_TAG: _enc_record(v)} if isinstance(v, Record) else None
        ),
    )


def _dec(value: Any) -> Any:
    def decode_special(data: Dict[str, Any]):
        if set(data.keys()) == {_RECORD_TAG}:
            return _dec_record(data[_RECORD_TAG])
        return NotImplemented

    return wire_json.decode_value(
        value,
        extra_markers=_RECORD_MARKERS,
        decode_special=decode_special,
    )


def _enc_record(record: Record) -> Dict[str, Any]:
    return {
        "key": _enc(record.key),
        "value": _enc(record.value),
        "origin": record.origin,
        "timestamp": record.timestamp,
        "headers": [[k, _enc(v)] for k, v in record.headers],
    }


def _dec_record(data: Dict[str, Any]) -> Record:
    return Record(
        key=_dec(data.get("key")),
        value=_dec(data.get("value")),
        origin=data.get("origin"),
        timestamp=data.get("timestamp"),
        headers=tuple((k, _dec(v)) for k, v in data.get("headers") or ()),
    )


async def _send(writer: asyncio.StreamWriter, message: Dict[str, Any]) -> None:
    payload = json.dumps(message, default=str).encode()
    if len(payload) > _MAX_FRAME:
        raise ValueError(
            f"isolation frame too large ({len(payload)} bytes > "
            f"{_MAX_FRAME}); shrink the record batch"
        )
    writer.write(_LEN.pack(len(payload)) + payload)
    await writer.drain()


async def _recv(reader: asyncio.StreamReader) -> Dict[str, Any]:
    header = await reader.readexactly(_LEN.size)
    (size,) = _LEN.unpack(header)
    if size > _MAX_FRAME:
        raise RuntimeError(f"isolation frame too large: {size}")
    return json.loads(await reader.readexactly(size))


# --------------------------------------------------------------------- #
# parent side
# --------------------------------------------------------------------- #
class RemoteUserAgent:
    """Duck-typed stand-in for the user agent instance: same async
    surface (`init/start/close/set_context/process/read/commit/write/
    join/agent_info`) as the in-process object, but every call is an
    RPC to the child. Created by ``spawn()``."""

    def __init__(self) -> None:
        self._process: Optional[asyncio.subprocess.Process] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[str, asyncio.Future] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._socket_path = ""
        self._crashed: Optional[AgentProcessCrashed] = None
        self._closing = False

    # ---------------------------------------------------------------- #
    @classmethod
    async def spawn(
        cls,
        kind: str,
        configuration: Dict[str, Any],
        connect_timeout: float = 20.0,
    ) -> "RemoteUserAgent":
        self = cls()
        sock_dir = tempfile.mkdtemp(prefix="ls-agent-")
        self._socket_path = os.path.join(sock_dir, "agent.sock")
        connected: asyncio.Future = asyncio.get_event_loop().create_future()

        async def on_connect(reader, writer):
            if not connected.done():
                connected.set_result((reader, writer))

        server = await asyncio.start_unix_server(
            on_connect, path=self._socket_path
        )
        # child inherits the parent's interpreter + sys.path (the
        # framework must be importable; user code paths travel in the
        # init message, not argv)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in sys.path if p
        )
        # the child must never touch the parent's TPU: initializing a
        # second client on the same chip wedges both processes (and the
        # TPU plugin's sitecustomize may have set JAX_PLATFORMS in the
        # parent env, so setdefault would not protect)
        env["JAX_PLATFORMS"] = "cpu"
        self._process = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "langstream_tpu.agents.isolation",
            self._socket_path,
            env=env,
            stdout=None, stderr=None,  # user prints flow to the pod log
        )
        try:
            self._reader, self._writer = await asyncio.wait_for(
                connected, connect_timeout
            )
        except asyncio.TimeoutError:
            await self.close()  # kill + reap + remove the socket tempdir
            raise AgentProcessCrashed(
                f"isolated agent worker did not connect within "
                f"{connect_timeout:.0f}s"
            ) from None
        finally:
            server.close()
        self._reader_task = asyncio.ensure_future(self._read_loop())
        try:
            await self._call(
                "boot", kind=kind, configuration=_enc(configuration)
            )
        except BaseException:
            # bad className / failing user init(): don't leak the child,
            # the reader task, or the socket tempdir on every deploy retry
            await self.close()
            raise
        return self

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                message = await _recv(self._reader)
                future = self._pending.pop(message.get("id"), None)
                if future is not None and not future.done():
                    future.set_result(message)
        except asyncio.CancelledError:
            raise
        except BaseException as error:  # noqa: BLE001 — ANY reader death
            if self._closing:
                # the child's clean EOF after our close RPC is not a
                # crash (marking it one would report crashed=true on
                # /info for every normal shutdown) — but in-flight RPCs
                # (a service join() blocking in the child) must still
                # resolve or their awaiters hang forever
                closed = RuntimeError("isolated agent closed")
                for future in self._pending.values():
                    if not future.done():
                        future.set_exception(closed)
                self._pending.clear()
                return
            # must fail fast: a decode error (oversized frame, bad JSON)
            # that killed only the reader task would leave every
            # in-flight and future call hanging forever
            returncode: Any = None
            if self._process is not None and isinstance(
                error, (asyncio.IncompleteReadError, ConnectionError, OSError)
            ):
                try:
                    returncode = await asyncio.wait_for(
                        self._process.wait(), timeout=5.0
                    )
                except asyncio.TimeoutError:
                    returncode = "unknown (socket closed, process alive)"
            detail = (
                f"exit code {returncode}" if returncode is not None
                else f"transport error: {error!r}"
            )
            self._crashed = AgentProcessCrashed(
                f"isolated agent process died ({detail})"
            )
            for future in self._pending.values():
                if not future.done():
                    future.set_exception(self._crashed)
            self._pending.clear()

    async def _call(self, method: str, **kwargs) -> Any:
        if self._crashed is not None:
            raise self._crashed
        assert self._writer is not None
        request_id = uuid.uuid4().hex
        future: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[request_id] = future
        try:
            await _send(
                self._writer,
                {"id": request_id, "method": method, **kwargs},
            )
        except (ConnectionError, OSError) as error:
            self._pending.pop(request_id, None)
            raise self._crashed or AgentProcessCrashed(
                f"isolated agent socket write failed: {error}"
            ) from error
        except BaseException:
            # e.g. oversize-frame ValueError: the request never went out,
            # so its future must not linger in _pending (it would log
            # 'exception was never retrieved' when the child later dies)
            self._pending.pop(request_id, None)
            raise
        response = await future
        if "error" in response:
            error = response["error"]
            raise RemoteAgentError(
                error.get("message", "remote agent error"),
                error.get("traceback", ""),
            )
        return _dec(response.get("result"))

    # -------------------------- SPI surface ------------------------- #
    async def init(self, configuration: Dict[str, Any]) -> None:
        # configuration already travelled in the boot message; the
        # child ran user init() there so import/config errors surface
        # at deploy time like in-process agents
        return None

    async def set_context(self, context: Any) -> None:
        # only the serializable subset crosses (the reference's gRPC
        # context carries the same: persistent dir + ids, agent.proto)
        await self._call("set_context", context={
            "agent_id": getattr(context, "agent_id", None),
            "application_id": getattr(context, "application_id", None),
            "persistent_state_directory": getattr(
                context, "persistent_state_directory", None
            ),
        })

    async def start(self) -> None:
        await self._call("start")

    async def process(self, record: Record) -> List[Record]:
        # the child already coerced loose user returns; _dec in _call
        # materialized the Record envelopes
        return await self._call("process", record=_enc_record(record)) or []

    async def read(self) -> List[Record]:
        return await self._call("read") or []

    async def commit(self, records: List[Record]) -> None:
        await self._call(
            "commit", records=[_enc_record(r) for r in records]
        )

    async def permanent_failure(self, record: Record, error: BaseException) -> None:
        await self._call(
            "permanent_failure",
            record=_enc_record(record), message=str(error),
        )

    async def write(self, record: Record) -> None:
        await self._call("write", record=_enc_record(record))

    async def join(self) -> None:
        await self._call("join")

    def agent_info(self) -> Dict[str, Any]:
        return {"isolation": "process", "crashed": self._crashed is not None}

    async def close(self) -> None:
        self._closing = True
        if self._crashed is None and self._writer is not None:
            try:
                await asyncio.wait_for(self._call("close"), timeout=10.0)
            except (Exception, asyncio.TimeoutError):
                # includes the 'isolated agent closed' RuntimeError the
                # read loop sets on pending futures when the child EOFs
                # before the close response — cleanup below must run
                # regardless
                pass
        if self._writer is not None:
            self._writer.close()
        if self._process is not None and self._process.returncode is None:
            try:
                self._process.terminate()
                await asyncio.wait_for(self._process.wait(), timeout=5.0)
            except (asyncio.TimeoutError, ProcessLookupError):
                try:
                    self._process.kill()
                except ProcessLookupError:
                    pass
        if self._reader_task is not None:
            self._reader_task.cancel()
        try:
            os.unlink(self._socket_path)
            os.rmdir(os.path.dirname(self._socket_path))
        except OSError:
            pass


# --------------------------------------------------------------------- #
# child side (python -m langstream_tpu.agents.isolation <socket>)
# --------------------------------------------------------------------- #
async def _worker(socket_path: str) -> None:
    from langstream_tpu.agents.python_agents import _maybe_await

    reader, writer = await asyncio.open_unix_connection(socket_path)
    agent: Any = None
    lock = asyncio.Lock()  # user agents are single-threaded, like the SPI

    async def handle(message: Dict[str, Any]) -> None:
        nonlocal agent
        response: Dict[str, Any] = {"id": message.get("id")}
        try:
            method = message["method"]
            if method == "boot":
                configuration = _dec(message["configuration"])
                class_name = configuration.get("className")
                if not class_name:
                    raise ValueError(
                        "python agent requires 'className' configuration"
                    )
                # this child belongs to ONE app, so the reference's flat
                # PYTHONPATH semantics apply (PythonGrpcServer.java:81-85:
                # python/ + python/lib, in that precedence, ahead of
                # site-packages): user modules AND their third-party
                # deps import absolutely — no namespacing needed here,
                # the process IS the namespace
                fresh = [
                    str(p) for p in configuration.get("pythonPath") or []
                    if p and str(p) not in sys.path
                ]
                sys.path[:0] = fresh
                from langstream_tpu.runtime.registry import load_class

                cls = load_class(class_name)
                agent = cls()
                if hasattr(agent, "init"):
                    await _maybe_await(agent.init(configuration))
            elif method == "set_context":
                if hasattr(agent, "set_context"):
                    import types

                    await _maybe_await(agent.set_context(
                        types.SimpleNamespace(**message["context"])
                    ))
            elif method == "start":
                if hasattr(agent, "start"):
                    await _maybe_await(agent.start())
            elif method == "process":
                source_record = _dec_record(message["record"])
                async with lock:
                    results = await _maybe_await(agent.process(source_record))
                # same coercion the in-process path applies
                # (python_agents.py process_record): bare values inherit
                # the source record's origin
                coerced = [
                    record_from_value(r, origin=source_record.origin)
                    for r in (results or [])
                ]
                response["result"] = [
                    {"__record__": _enc_record(r)} for r in coerced
                ]
            elif method == "read":
                async with lock:
                    results = await _maybe_await(agent.read())
                coerced = [record_from_value(r) for r in (results or [])]
                response["result"] = [
                    {"__record__": _enc_record(r)} for r in coerced
                ]
            elif method == "commit":
                if hasattr(agent, "commit"):
                    async with lock:
                        await _maybe_await(agent.commit(
                            [_dec_record(r) for r in message["records"]]
                        ))
            elif method == "permanent_failure":
                if hasattr(agent, "permanent_failure"):
                    await _maybe_await(agent.permanent_failure(
                        _dec_record(message["record"]),
                        RuntimeError(message.get("message", "")),
                    ))
                else:
                    raise RuntimeError(message.get("message", ""))
            elif method == "write":
                async with lock:
                    await _maybe_await(agent.write(_dec_record(message["record"])))
            elif method == "join":
                if hasattr(agent, "join"):
                    await _maybe_await(agent.join())
                elif hasattr(agent, "main"):
                    await _maybe_await(agent.main())
                else:
                    await asyncio.Event().wait()
            elif method == "close":
                if agent is not None and hasattr(agent, "close"):
                    await _maybe_await(agent.close())
                await _send(writer, response)
                writer.close()
                # stdio is a block-buffered pipe into the pod log; flush
                # or a short-lived agent loses its print() diagnostics
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(0)
            else:
                raise ValueError(f"unknown method {method!r}")
        except BaseException as error:  # noqa: BLE001 — report, don't die
            import traceback

            response["error"] = {
                "message": f"{type(error).__name__}: {error}",
                "traceback": traceback.format_exc(),
            }
        try:
            await _send(writer, response)
        except (ConnectionError, OSError):
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(1)  # parent gone; nothing to serve

    while True:
        try:
            message = await _recv(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            # parent died or closed: exit quietly (reference child dies
            # with its Java parent the same way)
            return
        # each request is its own task so a blocking join() (service
        # agents) cannot starve close()/reads; the per-agent lock keeps
        # record-path calls sequential
        asyncio.ensure_future(handle(message))


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    # the TPU plugin's sitecustomize force-selects its platform at
    # interpreter start, overriding the JAX_PLATFORMS=cpu the parent set
    # in our env — override it back BEFORE user code can import jax, or
    # a user `import jax` grabs (and wedges) the parent's chip. Only
    # needed when a sitecustomize already imported jax; otherwise the
    # env var governs and jax-free agents skip the heavy import.
    if "jax" in sys.modules:
        sys.modules["jax"].config.update("jax_platforms", "cpu")
    asyncio.run(_worker(sys.argv[1]))
