"""TransformContext: the uniform mutable view every GenAI step operates on.

Equivalent of the reference's ``MutableRecord``
(``langstream-agents/langstream-agents-commons/src/main/java/ai/langstream/ai/agents/commons/MutableRecord.java:58``):
a record is lifted into a mutable key/value/headers structure with
path-addressable fields (``value``, ``value.question``, ``key.id``,
``properties.header-name``, ``destinationTopic``, ``timestamp``), steps
mutate it in memory, and it is lowered back to a :class:`Record` at the end
of the step chain. JSON-string values are parsed on demand so dotted paths
work over serialized payloads, mirroring the reference's schema converters.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.api.records import Record, now_millis


class TransformContext:
    def __init__(self, record: Record) -> None:
        self.record = record
        self.key = record.key
        self.value = record.value
        self.properties: Dict[str, Any] = record.headers_as_dict()
        self.destination_topic: Optional[str] = None
        self.timestamp = record.timestamp
        self.dropped = False

    # ------------------------------------------------------------------ #
    # expression-language context
    # ------------------------------------------------------------------ #
    def el_context(self) -> Dict[str, Any]:
        return {
            "key": self._structured(self.key),
            "value": self._structured(self.value),
            "properties": dict(self.properties),
            "origin": self.record.origin,
            "topicName": self.record.origin,
            "timestamp": self.timestamp,
            "eventTime": self.timestamp,
        }

    @staticmethod
    def _structured(value: Any) -> Any:
        """Parse JSON strings/bytes so dotted paths reach inside them."""
        if isinstance(value, bytes):
            try:
                value = value.decode("utf-8")
            except UnicodeDecodeError:
                return value
        if isinstance(value, str):
            stripped = value.strip()
            if stripped.startswith(("{", "[")):
                try:
                    return json.loads(stripped)
                except json.JSONDecodeError:
                    return value
        return value

    # ------------------------------------------------------------------ #
    # path-addressable fields
    # ------------------------------------------------------------------ #
    def get_field(self, path: str) -> Any:
        root, rest = self._split(path)
        if root == "value":
            node = self._structured(self.value)
        elif root == "key":
            node = self._structured(self.key)
        elif root == "properties":
            node = self.properties
        elif root == "destinationTopic":
            return self.destination_topic
        elif root == "timestamp":
            return self.timestamp
        else:
            raise KeyError(f"unknown field root {root!r} in path {path!r}")
        for part in rest:
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                return None
        return node

    def set_field(self, path: str, new_value: Any) -> None:
        root, rest = self._split(path)
        if root == "destinationTopic":
            self.destination_topic = new_value
            return
        if root == "timestamp":
            self.timestamp = new_value
            return
        if root == "properties":
            if not rest:
                raise KeyError("cannot replace the whole properties map")
            self.properties[rest[0]] = new_value
            return
        if root not in ("value", "key"):
            raise KeyError(f"unknown field root {root!r} in path {path!r}")
        if not rest:
            setattr(self, root, new_value)
            return
        container = self._structured(getattr(self, root))
        if container is None:
            container = {}
        elif not isinstance(container, dict):
            # silently discarding a scalar value would lose data (e.g. the
            # chunk text after text-splitter); fail loudly like the
            # reference's schema layer would
            raise ValueError(
                f"cannot set field {'.'.join(rest)!r} on non-object {root} "
                f"of type {type(container).__name__}; convert the record "
                "first (e.g. document-to-json)"
            )
        node = container
        for part in rest[:-1]:
            nxt = node.get(part)
            if not isinstance(nxt, dict):
                nxt = {}
                node[part] = nxt
            node = nxt
        node[rest[-1]] = new_value
        setattr(self, root, container)

    def delete_field(self, path: str) -> None:
        root, rest = self._split(path)
        if root == "properties" and rest:
            self.properties.pop(rest[0], None)
            return
        if root in ("value", "key"):
            if not rest:
                setattr(self, root, None)
                return
            container = self._structured(getattr(self, root))
            node = container
            for part in rest[:-1]:
                if not isinstance(node, dict):
                    return
                node = node.get(part)
            if isinstance(node, dict):
                node.pop(rest[-1], None)
            setattr(self, root, container)

    @staticmethod
    def _split(path: str):
        parts = path.split(".")
        return parts[0], parts[1:]

    # ------------------------------------------------------------------ #
    # lowering
    # ------------------------------------------------------------------ #
    def to_record(self) -> Record:
        return Record(
            value=self.value,
            key=self.key,
            origin=self.record.origin,
            timestamp=self.timestamp or now_millis(),
            headers=tuple(self.properties.items()),
        )
