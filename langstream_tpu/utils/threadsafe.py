"""Snapshot-tolerant reads of another thread's containers.

CPython guarantees individual dict/list/set operations are atomic, but
ITERATION over a container is not: a writer inserting a key mid-iteration
raises ``RuntimeError: dictionary changed size during iteration`` (dicts,
sets, ``WeakSet``). That is exactly how a metrics scrape racing a
supervisor rebuild — which constructs the replacement engine on the
dying engine thread and registers it in ``_LIVE_ENGINES`` — or racing
the engine thread's first write of a new ``tokens_wasted`` reason can
take down an HTTP handler (the failure class PR 10 fixed by hand in
``build_heartbeat``; the lock-discipline pass now flags it, and these
helpers are the sanctioned read-side pattern for state annotated
``owned-by`` another thread).

Readers here never block the writer: retry the snapshot a few times and,
if the container is persistently hot, return the empty snapshot — for a
gauge scrape a missed poll is strictly better than a 500.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Tuple

_ATTEMPTS = 8


def stable_list(iterable: Iterable[Any], attempts: int = _ATTEMPTS) -> List[Any]:
    """``list(iterable)`` retried across concurrent resizes."""
    for _ in range(attempts):
        try:
            return list(iterable)
        except RuntimeError:  # changed size during iteration
            continue
    return []


def stable_items(
    mapping: Dict[Any, Any], attempts: int = _ATTEMPTS
) -> List[Tuple[Any, Any]]:
    """``list(mapping.items())`` retried across concurrent resizes."""
    for _ in range(attempts):
        try:
            return list(mapping.items())
        except RuntimeError:
            continue
    return []
