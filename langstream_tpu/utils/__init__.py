"""Shared small utilities (wire codecs, …)."""
