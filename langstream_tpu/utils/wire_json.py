"""Escape-aware JSON value codec shared by the wire surfaces.

One place for the ``{"__b64__": …}`` binary escape used by the durable
log runtime (`topics/log/codec.py`), the process-isolation boundary
(`agents/isolation.py`), and any future JSON-framed transport.
Deliberately NOT pickle — nothing executable crosses a wire.

Domain: the JSON-shaped record value domain (str/num/bool/None, lists,
dicts with string keys) plus ``bytes``. Literal user dicts whose key
set collides with an escape marker are wrapped in ``{"__esc__": …}`` so
the codec stays bijective over its domain (a plain tag-check codec
would silently decode ``{"__b64__": "x"}`` written BY THE USER into
bytes). Non-string dict keys are stringified — a JSON limitation shared
by every broker codec in this framework.

Transports may register additional markers (the isolation boundary adds
``__record__``) by passing ``extra_markers``.
"""

from __future__ import annotations

import base64
from typing import Any, Callable, Dict, Optional, Tuple

BYTES_TAG = "__b64__"
ESC_TAG = "__esc__"

_BASE_MARKERS: Tuple[frozenset, ...] = (
    frozenset((BYTES_TAG,)),
    frozenset((ESC_TAG,)),
)


def encode_value(
    value: Any,
    *,
    extra_markers: Tuple[frozenset, ...] = (),
    encode_special: Optional[Callable[[Any], Optional[Dict[str, Any]]]] = None,
) -> Any:
    """Encode ``value`` into the JSON-safe escaped form.

    ``encode_special(value)`` may return a marker dict for
    transport-specific types (e.g. Records) or None to fall through."""
    if encode_special is not None:
        special = encode_special(value)
        if special is not None:
            return special
    if isinstance(value, (bytes, bytearray, memoryview)):
        return {BYTES_TAG: base64.b64encode(bytes(value)).decode("ascii")}
    if isinstance(value, dict):
        encoded = {
            str(k): encode_value(
                v, extra_markers=extra_markers, encode_special=encode_special
            )
            for k, v in value.items()
        }
        keys = frozenset(encoded.keys())
        if keys in _BASE_MARKERS or keys in extra_markers:
            return {ESC_TAG: encoded}
        return encoded
    if isinstance(value, (list, tuple)):
        return [
            encode_value(
                v, extra_markers=extra_markers, encode_special=encode_special
            )
            for v in value
        ]
    return value


def decode_value(
    value: Any,
    *,
    extra_markers: Tuple[frozenset, ...] = (),
    decode_special: Optional[Callable[[Dict[str, Any]], Any]] = None,
) -> Any:
    """Inverse of :func:`encode_value` (pass the same
    ``extra_markers``). ``decode_special(dict)`` may claim a marker dict
    (returning the decoded object) or return the sentinel
    ``NotImplemented`` to fall through."""

    def rec(v: Any) -> Any:
        return decode_value(
            v, extra_markers=extra_markers, decode_special=decode_special
        )

    if isinstance(value, dict):
        keys = set(value.keys())
        if keys == {BYTES_TAG}:
            return base64.b64decode(value[BYTES_TAG])
        if keys == {ESC_TAG} and isinstance(value[ESC_TAG], dict):
            inner_keys = frozenset(value[ESC_TAG].keys())
            # only unwrap what OUR encoder wraps: an inner dict whose
            # key set is itself a marker set. Anything else is legacy
            # data the pre-escape codec passed through verbatim — a
            # user's literal {'__esc__': {...}} must decode as itself
            if inner_keys in _BASE_MARKERS or inner_keys in extra_markers:
                return {k: rec(v) for k, v in value[ESC_TAG].items()}
        if decode_special is not None:
            special = decode_special(value)
            if special is not NotImplemented:
                return special
        return {k: rec(v) for k, v in value.items()}
    if isinstance(value, list):
        return [rec(v) for v in value]
    return value
