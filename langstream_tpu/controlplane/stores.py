"""Application and global-metadata stores.

Reference SPIs: ``langstream-api/.../storage/ApplicationStore.java:29``
(tenant app CRUD + status + logs) and ``GlobalMetadataStore``. The
reference's production impl stores apps AS Kubernetes custom resources
(``KubernetesApplicationStore.java:66``); here the durable backend is a
filesystem document store (one JSON doc per app under the tenant
directory), with an in-memory twin for tests — the K8s deployer consumes
the same documents when scheduling onto a cluster.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import threading
import time
from typing import Any, Dict, List, Optional, Protocol


@dataclasses.dataclass
class StoredApplication:
    """The stored form of a deployed app: the raw (unresolved) application
    document plus deployment bookkeeping. Secrets are stored separately
    from the public document and never listed."""

    application_id: str
    tenant: str
    definition: Dict[str, Any]          # serialized Application (no secrets)
    instance: Dict[str, Any]
    secrets: Dict[str, Any]
    code_archive_id: Optional[str] = None
    checksum: Optional[str] = None
    status: str = "CREATED"             # CREATED|DEPLOYING|DEPLOYED|ERROR|DELETING
    status_detail: str = ""
    created_at: float = dataclasses.field(default_factory=time.time)
    updated_at: float = dataclasses.field(default_factory=time.time)

    def public_view(self) -> Dict[str, Any]:
        return {
            "application-id": self.application_id,
            "tenant": self.tenant,
            "application": self.definition,
            "instance": _redact_instance(self.instance),
            "code-archive-id": self.code_archive_id,
            "checksum": self.checksum,
            "status": {"status": self.status, "detail": self.status_detail},
            "created-at": self.created_at,
            "updated-at": self.updated_at,
        }


def _redact_instance(instance: Dict[str, Any]) -> Dict[str, Any]:
    """Drop credential-ish keys from cluster configurations before they
    leave the control plane (the reference redacts secrets the same way by
    storing them in a separate Secret resource)."""
    def clean(value: Any) -> Any:
        if isinstance(value, dict):
            return {
                k: ("***" if _sensitive(k) else clean(v))
                for k, v in value.items()
            }
        if isinstance(value, list):
            return [clean(v) for v in value]
        return value

    return clean(instance or {})


def _sensitive(key: str) -> bool:
    lowered = key.lower().replace("_", "-")
    return any(
        token in lowered
        for token in ("password", "secret", "token", "access-key", "api-key")
    )


class ApplicationStore(Protocol):
    def put(self, app: StoredApplication) -> None: ...
    def get(self, tenant: str, application_id: str) -> Optional[StoredApplication]: ...
    def delete(self, tenant: str, application_id: str) -> None: ...
    def list(self, tenant: str) -> List[StoredApplication]: ...
    def on_tenant_deleted(self, tenant: str) -> None: ...


class InMemoryApplicationStore:
    """Reference analogue: the runtime-tester's
    ``InMemoryApplicationStore.java:42``."""

    def __init__(self) -> None:
        self._apps: Dict[str, Dict[str, StoredApplication]] = {}
        self._lock = threading.Lock()

    def put(self, app: StoredApplication) -> None:
        app.updated_at = time.time()
        with self._lock:
            self._apps.setdefault(app.tenant, {})[app.application_id] = app

    def get(self, tenant: str, application_id: str) -> Optional[StoredApplication]:
        with self._lock:
            return self._apps.get(tenant, {}).get(application_id)

    def delete(self, tenant: str, application_id: str) -> None:
        with self._lock:
            self._apps.get(tenant, {}).pop(application_id, None)

    def list(self, tenant: str) -> List[StoredApplication]:
        with self._lock:
            return sorted(
                self._apps.get(tenant, {}).values(),
                key=lambda app: app.application_id,
            )

    def on_tenant_deleted(self, tenant: str) -> None:
        with self._lock:
            self._apps.pop(tenant, None)


class FileSystemApplicationStore:
    """One JSON document per app: ``<root>/<tenant>/<app-id>.json``.
    Writes are atomic (tmp + rename) so a crashed control plane never
    leaves a torn document."""

    def __init__(self, root: str) -> None:
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, tenant: str, application_id: str) -> pathlib.Path:
        for part in (tenant, application_id):
            if "/" in part or os.sep in part or part in ("", ".", ".."):
                raise ValueError(
                    f"invalid tenant/application id {tenant!r}/{application_id!r}"
                )
        return self.root / tenant / f"{application_id}.json"

    def put(self, app: StoredApplication) -> None:
        app.updated_at = time.time()
        path = self._path(app.tenant, app.application_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        doc = dataclasses.asdict(app)
        with self._lock:
            tmp = path.with_suffix(".tmp")
            tmp.write_text(json.dumps(doc))
            tmp.replace(path)

    def get(self, tenant: str, application_id: str) -> Optional[StoredApplication]:
        path = self._path(tenant, application_id)
        with self._lock:
            if not path.exists():
                return None
            doc = json.loads(path.read_text())
        return StoredApplication(**doc)

    def delete(self, tenant: str, application_id: str) -> None:
        path = self._path(tenant, application_id)
        with self._lock:
            if path.exists():
                path.unlink()

    def list(self, tenant: str) -> List[StoredApplication]:
        directory = self.root / tenant
        with self._lock:
            if not directory.is_dir():
                return []
            docs = [
                json.loads(p.read_text())
                for p in sorted(directory.glob("*.json"))
            ]
        return [StoredApplication(**doc) for doc in docs]

    def on_tenant_deleted(self, tenant: str) -> None:
        directory = self.root / tenant
        with self._lock:
            if directory.is_dir():
                for path in directory.glob("*.json"):
                    path.unlink()


class GlobalMetadataStore:
    """Cluster-global key/value metadata (reference:
    ``GlobalMetadataStore.java`` — ConfigMap-backed in production). The
    tenant registry persists through this."""

    def __init__(self, path: Optional[str] = None) -> None:
        self._path = pathlib.Path(path) if path else None
        self._data: Dict[str, Any] = {}
        self._lock = threading.Lock()
        if self._path and self._path.exists():
            self._data = json.loads(self._path.read_text())

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._data.get(key, default)

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value
            self._flush()

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._flush()

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._data)

    def _flush(self) -> None:
        if self._path is None:
            return
        self._path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self._path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self._data))
        tmp.replace(self._path)


class KubernetesApplicationStore:
    """Applications stored AS Application custom resources, secrets in a
    sibling k8s Secret (reference: ``langstream-k8s-storage/.../apps/
    KubernetesApplicationStore.java:66`` — the cluster is the database,
    so every control-plane replica sees the same state and the operator
    reconciles straight from what the store wrote).

    Tenants map to namespaces; ``kube`` is any client with the
    apply/get/list/delete verb interface (real REST client in-cluster,
    the in-memory mock in tests).
    """

    _SECRET_PREFIX = "langstream-app-"

    def __init__(self, kube) -> None:
        self.kube = kube

    # -- mapping -------------------------------------------------------- #
    def _to_manifests(self, app: StoredApplication):
        import base64

        from langstream_tpu.deployer.crds import ApplicationCustomResource

        cr = ApplicationCustomResource(
            name=app.application_id,
            namespace=app.tenant,
            application=app.definition,
            instance=app.instance,
            code_archive_id=app.code_archive_id,
            checksum=app.checksum,
        )
        manifest = cr.to_manifest()
        manifest["metadata"].setdefault("annotations", {}).update({
            "langstream.tpu/created-at": str(app.created_at),
            "langstream.tpu/updated-at": str(app.updated_at),
        })
        secret = {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {
                "name": f"{self._SECRET_PREFIX}{app.application_id}",
                "namespace": app.tenant,
            },
            "data": {
                "secrets.json": base64.b64encode(
                    json.dumps(app.secrets or {}).encode()
                ).decode()
            },
        }
        return manifest, secret

    def _from_manifests(self, doc, secret) -> StoredApplication:
        import base64

        from langstream_tpu.deployer.crds import ApplicationCustomResource

        cr = ApplicationCustomResource.from_manifest(doc)
        secrets: Dict[str, Any] = {}
        if secret:
            raw = (secret.get("data") or {}).get("secrets.json")
            if raw:
                secrets = json.loads(base64.b64decode(raw))
        annotations = doc.get("metadata", {}).get("annotations", {}) or {}
        status = doc.get("status", {}) or {}
        return StoredApplication(
            application_id=cr.name,
            tenant=cr.namespace,
            definition=cr.application,
            instance=cr.instance,
            secrets=secrets,
            code_archive_id=cr.code_archive_id,
            checksum=cr.checksum,
            status=status.get("phase", "CREATED"),
            status_detail=status.get("detail", ""),
            created_at=float(annotations.get(
                "langstream.tpu/created-at", 0.0
            ) or 0.0),
            updated_at=float(annotations.get(
                "langstream.tpu/updated-at", 0.0
            ) or 0.0),
        )

    # -- verbs ---------------------------------------------------------- #
    def put(self, app: StoredApplication) -> None:
        app.updated_at = time.time()
        manifest, secret = self._to_manifests(app)
        self.kube.apply(secret)
        self.kube.apply(manifest)

    def get(self, tenant: str, application_id: str) -> Optional[StoredApplication]:
        doc = self.kube.get("Application", tenant, application_id)
        if doc is None:
            return None
        secret = self.kube.get(
            "Secret", tenant, f"{self._SECRET_PREFIX}{application_id}"
        )
        return self._from_manifests(doc, secret)

    def delete(self, tenant: str, application_id: str) -> None:
        self.kube.delete("Application", tenant, application_id)
        self.kube.delete(
            "Secret", tenant, f"{self._SECRET_PREFIX}{application_id}"
        )

    def list(self, tenant: str) -> List[StoredApplication]:
        out = []
        for doc in self.kube.list("Application", tenant):
            name = doc["metadata"]["name"]
            secret = self.kube.get(
                "Secret", tenant, f"{self._SECRET_PREFIX}{name}"
            )
            out.append(self._from_manifests(doc, secret))
        return sorted(out, key=lambda app: app.application_id)

    def on_tenant_deleted(self, tenant: str) -> None:
        for doc in self.kube.list("Application", tenant):
            self.delete(tenant, doc["metadata"]["name"])


class KubernetesGlobalMetadataStore:
    """Global metadata in one ConfigMap (reference:
    ``KubernetesGlobalMetadataStore`` — the tenant registry and other
    cluster-wide state survive control-plane restarts through the
    cluster itself). Same get/put/delete/keys surface as
    :class:`GlobalMetadataStore`."""

    CONFIGMAP = "langstream-global-metadata"

    def __init__(self, kube, namespace: str = "default") -> None:
        self.kube = kube
        self.namespace = namespace
        self._lock = threading.Lock()

    def _load(self) -> Dict[str, Any]:
        doc = self.kube.get("ConfigMap", self.namespace, self.CONFIGMAP)
        if doc is None:
            return {}
        raw = (doc.get("data") or {}).get("metadata.json")
        return json.loads(raw) if raw else {}

    def _store(self, data: Dict[str, Any]) -> None:
        self.kube.apply({
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": self.CONFIGMAP, "namespace": self.namespace,
            },
            "data": {"metadata.json": json.dumps(data)},
        })

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._load().get(key, default)

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            data = self._load()
            data[key] = value
            self._store(data)

    def delete(self, key: str) -> None:
        with self._lock:
            data = self._load()
            if key in data:
                del data[key]
                self._store(data)

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._load())
