"""Control plane: application/tenant stores, code storage, deployment
service, and the REST webservice.

The reference's control plane is a Spring Boot webservice plus a K8s
operator (`langstream-webservice/`, `langstream-k8s-deployer/`,
`langstream-k8s-storage/` — SURVEY §2.6). Here the same responsibilities
are native Python services designed around the single-binary local runner
and the TPU deployer:

- :mod:`codestorage` — app archive storage (CodeStorage SPI).
- :mod:`stores`      — ApplicationStore / GlobalMetadataStore SPIs with
  in-memory and filesystem backends.
- :mod:`tenants`     — tenant registry + resource-limit checking.
- :mod:`service`     — ApplicationService: parse/validate/deploy/delete.
- :mod:`webservice`  — aiohttp REST surface mirroring the reference's
  `/api/applications`, `/api/tenants`, `/api/archetypes` endpoints.
"""

from langstream_tpu.controlplane.codestorage import (  # noqa: F401
    CodeStorage,
    LocalDiskCodeStorage,
    create_code_storage,
)
from langstream_tpu.controlplane.stores import (  # noqa: F401
    ApplicationStore,
    FileSystemApplicationStore,
    KubernetesApplicationStore,
    KubernetesGlobalMetadataStore,
    GlobalMetadataStore,
    InMemoryApplicationStore,
    StoredApplication,
)
from langstream_tpu.controlplane.tenants import (  # noqa: F401
    TenantAlreadyExists,
    TenantConfiguration,
    TenantNotFound,
    TenantService,
)
from langstream_tpu.controlplane.service import (  # noqa: F401
    ApplicationAlreadyExists,
    ApplicationNotFound,
    ApplicationService,
    ResourceLimitExceeded,
)
