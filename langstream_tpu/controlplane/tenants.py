"""Tenant registry and per-tenant resource limits.

Reference: tenants CRUD in ``langstream-webservice/.../common/
TenantResource.java`` and quota enforcement in
``langstream-k8s-deployer/.../limits/ApplicationResourceLimitsChecker.java``
(an app's total resource units = Σ replicas × cpu-size across agents,
checked against the tenant's ``maxTotalResourceUnits`` before deploy).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from langstream_tpu.controlplane.stores import GlobalMetadataStore
from langstream_tpu.model.application import Application

_TENANTS_KEY = "tenants"


class TenantNotFound(KeyError):
    pass


class TenantAlreadyExists(ValueError):
    pass


@dataclasses.dataclass
class TenantConfiguration:
    name: str
    # 0 = unlimited (reference default)
    max_total_resource_units: int = 0
    created_at: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "TenantConfiguration":
        return cls(
            name=doc["name"],
            max_total_resource_units=int(
                doc.get("max_total_resource_units", 0)
                or doc.get("max-total-resource-units", 0)
                or 0
            ),
            created_at=doc.get("created_at", time.time()),
        )


def application_resource_units(application: Application) -> float:
    """Σ over agents of replicas × size — the unit the tenant quota is
    denominated in (reference ``ApplicationResourceLimitsChecker``)."""
    total = 0.0
    for module in application.modules.values():
        for pipeline in module.pipelines.values():
            for agent in pipeline.agents:
                resources = agent.resources
                total += float(resources.parallelism) * float(resources.size)
    return total


class TenantService:
    def __init__(self, metadata_store: Optional[GlobalMetadataStore] = None):
        self._store = metadata_store or GlobalMetadataStore()

    def _all(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._store.get(_TENANTS_KEY, {}) or {})

    def create(
        self, name: str, configuration: Optional[Dict[str, Any]] = None
    ) -> TenantConfiguration:
        tenants = self._all()
        if name in tenants:
            raise TenantAlreadyExists(name)
        tenant = TenantConfiguration.from_dict(
            {"name": name, **(configuration or {})}
        )
        tenants[name] = tenant.to_dict()
        self._store.put(_TENANTS_KEY, tenants)
        return tenant

    def update(
        self, name: str, configuration: Dict[str, Any]
    ) -> TenantConfiguration:
        tenants = self._all()
        if name not in tenants:
            raise TenantNotFound(name)
        merged = {**tenants[name], **configuration, "name": name}
        tenant = TenantConfiguration.from_dict(merged)
        tenants[name] = tenant.to_dict()
        self._store.put(_TENANTS_KEY, tenants)
        return tenant

    def put(
        self, name: str, configuration: Optional[Dict[str, Any]] = None
    ) -> TenantConfiguration:
        """Create-or-update (the reference PUT semantics)."""
        try:
            return self.create(name, configuration)
        except TenantAlreadyExists:
            return self.update(name, configuration or {})

    def get(self, name: str) -> TenantConfiguration:
        tenants = self._all()
        if name not in tenants:
            raise TenantNotFound(name)
        return TenantConfiguration.from_dict(tenants[name])

    def exists(self, name: str) -> bool:
        return name in self._all()

    def delete(self, name: str) -> None:
        tenants = self._all()
        if name not in tenants:
            raise TenantNotFound(name)
        del tenants[name]
        self._store.put(_TENANTS_KEY, tenants)

    def list(self) -> List[TenantConfiguration]:
        return [
            TenantConfiguration.from_dict(doc)
            for _, doc in sorted(self._all().items())
        ]

    def check_resource_limit(
        self, name: str, new_app_units: float, current_units: float
    ) -> None:
        """Raise if deploying an app of ``new_app_units`` would push the
        tenant past its quota (``current_units`` = sum over its other
        deployed apps)."""
        tenant = self.get(name)
        limit = tenant.max_total_resource_units
        if limit and current_units + new_app_units > limit:
            from langstream_tpu.controlplane.service import ResourceLimitExceeded

            raise ResourceLimitExceeded(
                f"tenant {name!r}: app needs {new_app_units} units, "
                f"{current_units} in use, limit {limit}"
            )
