"""REST control-plane webservice (aiohttp).

Endpoint parity with the reference Spring Boot webservice
(``langstream-webservice/.../application/ApplicationResource.java:125-505``,
``common/TenantResource.java``, ``archetype/ArchetypeResource.java:50``):

- ``POST   /api/applications/{tenant}/{id}``  multipart deploy
  (fields: ``app`` zip, ``instance`` yaml, ``secrets`` yaml; ``?dry-run``)
- ``PUT    /api/applications/{tenant}/{id}``  update
- ``GET    /api/applications/{tenant}``       list
- ``GET    /api/applications/{tenant}/{id}``  describe (+status)
- ``DELETE /api/applications/{tenant}/{id}``
- ``GET    /api/applications/{tenant}/{id}/logs``
- ``GET    /api/applications/{tenant}/{id}/code``  archive download
- ``GET|PUT|DELETE /api/tenants[/{name}]``
- ``GET /api/archetypes/{tenant}``, ``GET /api/archetypes/{tenant}/{id}``,
  ``POST /api/archetypes/{tenant}/{id}/applications/{app-id}``

Auth: optional static bearer token (the reference's JWT admin auth slot —
``application.properties`` + ``langstream-auth-jwt``); token comparison is
constant-time.
"""

from __future__ import annotations

import hmac
import json
import logging
import os
from typing import Any, Dict, Optional

import yaml
from aiohttp import web

from langstream_tpu.controlplane.codestorage import CodeArchiveNotFound
from langstream_tpu.controlplane.service import (
    ApplicationAlreadyExists,
    ApplicationNotFound,
    ApplicationService,
    ResourceLimitExceeded,
    zip_directory,
)
from langstream_tpu.controlplane.tenants import (
    TenantAlreadyExists,
    TenantNotFound,
)

logger = logging.getLogger(__name__)


class ControlPlaneWebService:
    def __init__(
        self,
        service: ApplicationService,
        *,
        auth_token: Optional[str] = None,
        archetypes_path: Optional[str] = None,
    ) -> None:
        self.service = service
        self.auth_token = auth_token
        self.archetypes_path = archetypes_path
        self.app = web.Application(middlewares=[self._errors_middleware])
        self._routes()
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None
        self.port: Optional[int] = None

    # -- plumbing ----------------------------------------------------- #
    def _routes(self) -> None:
        add = self.app.router.add_route
        add("GET", "/api/applications/{tenant}", self.list_applications)
        add("POST", "/api/applications/{tenant}/{id}", self.deploy_application)
        add("PUT", "/api/applications/{tenant}/{id}", self.update_application)
        add("GET", "/api/applications/{tenant}/{id}", self.get_application)
        add("DELETE", "/api/applications/{tenant}/{id}", self.delete_application)
        add("GET", "/api/applications/{tenant}/{id}/logs", self.get_logs)
        add("GET", "/api/applications/{tenant}/{id}/code", self.download_code)
        add("GET", "/api/tenants", self.list_tenants)
        add("GET", "/api/tenants/{name}", self.get_tenant)
        add("PUT", "/api/tenants/{name}", self.put_tenant)
        add("POST", "/api/tenants/{name}", self.put_tenant)
        add("DELETE", "/api/tenants/{name}", self.delete_tenant)
        add("GET", "/api/archetypes/{tenant}", self.list_archetypes)
        add("GET", "/api/archetypes/{tenant}/{id}", self.get_archetype)
        add(
            "POST",
            "/api/archetypes/{tenant}/{id}/applications/{app_id}",
            self.deploy_from_archetype,
        )
        add("GET", "/healthz", self.healthz)

    @web.middleware
    async def _errors_middleware(self, request: web.Request, handler):
        if self.auth_token and request.path != "/healthz":
            header = request.headers.get("Authorization", "")
            token = header[7:] if header.startswith("Bearer ") else ""
            if not hmac.compare_digest(token, self.auth_token):
                return web.json_response(
                    {"error": "unauthorized"}, status=401
                )
        try:
            return await handler(request)
        except (
            ApplicationNotFound,
            TenantNotFound,
            CodeArchiveNotFound,
            FileNotFoundError,
        ) as err:
            return web.json_response({"error": str(err)}, status=404)
        except (ApplicationAlreadyExists, TenantAlreadyExists) as err:
            return web.json_response({"error": str(err)}, status=409)
        except ResourceLimitExceeded as err:
            return web.json_response({"error": str(err)}, status=429)
        except (ValueError, KeyError) as err:
            logger.info("bad request: %s", err)
            return web.json_response({"error": str(err)}, status=400)

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host, port)
        await self._site.start()
        self.port = self._site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # -- applications -------------------------------------------------- #
    async def _read_deploy_parts(self, request: web.Request):
        archive = instance_yaml = secrets_yaml = None
        reader = await request.multipart()
        async for part in reader:
            if part.name == "app":
                archive = await part.read(decode=False)
            elif part.name == "instance":
                instance_yaml = (await part.read(decode=False)).decode()
            elif part.name == "secrets":
                secrets_yaml = (await part.read(decode=False)).decode()
        if archive is None:
            raise ValueError("multipart field 'app' (zip) is required")
        return archive, instance_yaml, secrets_yaml

    async def deploy_application(self, request: web.Request) -> web.Response:
        return await self._deploy(request, update=False)

    async def update_application(self, request: web.Request) -> web.Response:
        return await self._deploy(request, update=True)

    async def _deploy(self, request: web.Request, update: bool) -> web.Response:
        tenant = request.match_info["tenant"]
        app_id = request.match_info["id"]
        archive, instance_yaml, secrets_yaml = await self._read_deploy_parts(
            request
        )
        dry_run = request.query.get("dry-run", "").lower() in ("1", "true")
        stored = await self.service.deploy(
            tenant, app_id, archive, instance_yaml, secrets_yaml,
            update=update, dry_run=dry_run,
        )
        return web.json_response(stored.public_view())

    async def list_applications(self, request: web.Request) -> web.Response:
        tenant = request.match_info["tenant"]
        return web.json_response(
            [app.public_view() for app in self.service.list(tenant)]
        )

    async def get_application(self, request: web.Request) -> web.Response:
        tenant = request.match_info["tenant"]
        app_id = request.match_info["id"]
        return web.json_response(self.service.get(tenant, app_id).public_view())

    async def delete_application(self, request: web.Request) -> web.Response:
        tenant = request.match_info["tenant"]
        app_id = request.match_info["id"]
        await self.service.delete(tenant, app_id)
        return web.json_response({"deleted": app_id})

    async def get_logs(self, request: web.Request) -> web.Response:
        tenant = request.match_info["tenant"]
        app_id = request.match_info["id"]
        lines = self.service.logs(tenant, app_id)
        return web.Response(text="\n".join(lines) + ("\n" if lines else ""))

    async def download_code(self, request: web.Request) -> web.Response:
        tenant = request.match_info["tenant"]
        app_id = request.match_info["id"]
        data = self.service.download_code(tenant, app_id)
        return web.Response(
            body=data,
            content_type="application/zip",
            headers={
                "Content-Disposition": f'attachment; filename="{app_id}.zip"'
            },
        )

    # -- tenants ------------------------------------------------------- #
    async def list_tenants(self, request: web.Request) -> web.Response:
        return web.json_response(
            {t.name: t.to_dict() for t in self.service.tenants.list()}
        )

    async def get_tenant(self, request: web.Request) -> web.Response:
        tenant = self.service.tenants.get(request.match_info["name"])
        return web.json_response(tenant.to_dict())

    async def put_tenant(self, request: web.Request) -> web.Response:
        config: Dict[str, Any] = {}
        if request.can_read_body and request.content_length:
            config = await request.json()
        tenant = self.service.tenants.put(request.match_info["name"], config)
        return web.json_response(tenant.to_dict())

    async def delete_tenant(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        for app in self.service.store.list(name):
            await self.service.delete(name, app.application_id)
        self.service.tenants.delete(name)
        self.service.on_tenant_deleted(name)
        return web.json_response({"deleted": name})

    # -- archetypes ---------------------------------------------------- #
    def _archetype_dir(self, archetype_id: str) -> str:
        if not self.archetypes_path:
            raise FileNotFoundError("no archetypes configured")
        path = os.path.normpath(
            os.path.join(self.archetypes_path, archetype_id)
        )
        root = os.path.normpath(self.archetypes_path)
        if not path.startswith(root + os.sep):
            raise ValueError("invalid archetype id")
        if not os.path.isdir(path):
            raise FileNotFoundError(f"archetype {archetype_id!r}")
        return path

    async def list_archetypes(self, request: web.Request) -> web.Response:
        if not self.archetypes_path or not os.path.isdir(self.archetypes_path):
            return web.json_response([])
        out = []
        for name in sorted(os.listdir(self.archetypes_path)):
            manifest = os.path.join(self.archetypes_path, name, "archetype.yaml")
            if os.path.isfile(manifest):
                with open(manifest) as f:
                    doc = yaml.safe_load(f) or {}
                out.append({"id": name, **(doc.get("archetype") or {})})
        return web.json_response(out)

    async def get_archetype(self, request: web.Request) -> web.Response:
        path = self._archetype_dir(request.match_info["id"])
        manifest = os.path.join(path, "archetype.yaml")
        doc: Dict[str, Any] = {}
        if os.path.isfile(manifest):
            with open(manifest) as f:
                doc = yaml.safe_load(f) or {}
        return web.json_response(
            {"id": request.match_info["id"], **(doc.get("archetype") or {})}
        )

    async def deploy_from_archetype(self, request: web.Request) -> web.Response:
        """Deploy an app from an archetype: body = JSON parameter values,
        injected as instance globals (the reference renders archetype
        parameters into the app's configuration the same way)."""
        tenant = request.match_info["tenant"]
        app_id = request.match_info["app_id"]
        path = self._archetype_dir(request.match_info["id"])
        parameters: Dict[str, Any] = {}
        if request.can_read_body and request.content_length:
            parameters = await request.json()
        archive = zip_directory(path)
        # merge parameters into the archetype's own instance (its cluster
        # configuration must survive; parameters only add/override globals)
        instance_doc: Dict[str, Any] = {}
        instance_path = os.path.join(path, "instance.yaml")
        if os.path.isfile(instance_path):
            with open(instance_path) as f:
                instance_doc = (yaml.safe_load(f) or {}).get("instance", {}) or {}
        merged_globals = {**(instance_doc.get("globals") or {}), **parameters}
        instance_doc["globals"] = merged_globals
        instance_yaml = yaml.safe_dump({"instance": instance_doc})
        stored = await self.service.deploy(
            tenant, app_id, archive, instance_yaml, None
        )
        return web.json_response(stored.public_view())

    async def healthz(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})


async def serve(
    service: ApplicationService,
    host: str = "0.0.0.0",
    port: int = 8090,
    **kwargs: Any,
) -> ControlPlaneWebService:
    ws = ControlPlaneWebService(service, **kwargs)
    await ws.start(host, port)
    return ws
