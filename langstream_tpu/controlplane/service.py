"""ApplicationService: the deploy/update/delete engine behind the REST
webservice and the CLI.

Reference: ``langstream-webservice/.../application/ApplicationService.java:54``
+ ``ApplicationResource.java:82``. Deploy flow parity (SURVEY §3.1): zip
upload → parse+validate (``ModelBuilder.buildApplicationInstance``) →
archive to CodeStorage → ApplicationStore put → the deployer picks it up.
Here the deployer is pluggable: the in-process executor actually runs the
app (the reference's runtime-tester/"docker run" pattern, server-side),
while the kubernetes deployer renders manifests for a cluster.
"""

from __future__ import annotations

import asyncio
import dataclasses
import io
import logging
import os
import tempfile
import time
import zipfile
from typing import Any, Dict, List, Optional, Protocol

import copy
import shutil

from langstream_tpu.compiler.parser import (
    application_checksum,
    parse_application_directory,
    resolve_placeholders,
)
from langstream_tpu.compiler.planner import build_execution_plan
from langstream_tpu.controlplane.codestorage import CodeStorage
from langstream_tpu.controlplane.stores import (
    ApplicationStore,
    StoredApplication,
)
from langstream_tpu.controlplane.tenants import (
    TenantService,
    application_resource_units,
)
from langstream_tpu.model.application import Application

logger = logging.getLogger(__name__)


class ApplicationNotFound(KeyError):
    pass


class ApplicationAlreadyExists(ValueError):
    pass


class ResourceLimitExceeded(ValueError):
    pass


class ApplicationExecutor(Protocol):
    """Where deployed apps actually run. Implementations: the in-process
    local executor below; the K8s deployer (``deployer`` package) which
    reconciles stored apps into StatefulSets."""

    async def deploy(self, stored: StoredApplication, application: Application) -> None: ...
    async def delete(self, tenant: str, application_id: str) -> None: ...
    def logs(self, tenant: str, application_id: str) -> List[str]: ...


class NullExecutor:
    """Store-only control plane (deployment handled by an external
    reconciler polling the store, as in the reference where the operator
    watches CRs)."""

    async def deploy(self, stored: StoredApplication, application: Application) -> None:
        return None

    async def delete(self, tenant: str, application_id: str) -> None:
        return None

    def logs(self, tenant: str, application_id: str) -> List[str]:
        return []


class LocalExecutor:
    """Runs each deployed app in-process with LocalApplicationRunner —
    the server-side twin of `langstream docker run` (reference
    ``LocalApplicationRunner.java:56``)."""

    def __init__(self) -> None:
        self._runners: Dict[tuple, Any] = {}
        self._logs: Dict[tuple, List[str]] = {}

    def _log(self, key: tuple, message: str) -> None:
        self._logs.setdefault(key, []).append(
            f"{time.strftime('%Y-%m-%dT%H:%M:%S')} {message}"
        )

    async def deploy(self, stored: StoredApplication, application: Application) -> None:
        from langstream_tpu.runtime.local import LocalApplicationRunner

        key = (stored.tenant, stored.application_id)
        await self.delete(*key)
        plan = build_execution_plan(application)
        runner = LocalApplicationRunner(plan)
        await runner.setup()
        await runner.start()
        self._runners[key] = runner
        self._log(key, f"deployed {stored.application_id} "
                       f"({len(plan.agents)} agents, {len(plan.topics)} topics)")

    async def delete(self, tenant: str, application_id: str) -> None:
        key = (tenant, application_id)
        runner = self._runners.pop(key, None)
        if runner is not None:
            await runner.stop()
            self._log(key, f"stopped {application_id}")

    def runner(self, tenant: str, application_id: str):
        return self._runners.get((tenant, application_id))

    def logs(self, tenant: str, application_id: str) -> List[str]:
        return list(self._logs.get((tenant, application_id), []))


class ApplicationService:
    def __init__(
        self,
        store: ApplicationStore,
        code_storage: CodeStorage,
        tenants: TenantService,
        executor: Optional[ApplicationExecutor] = None,
    ) -> None:
        self.store = store
        self.code_storage = code_storage
        self.tenants = tenants
        self.executor = executor or NullExecutor()
        self._work_root: Optional[str] = None

    # -- parse ------------------------------------------------------- #
    def _materialize(
        self,
        tenant: str,
        application_id: str,
        archive: bytes,
        instance_yaml: Optional[str],
        secrets_yaml: Optional[str],
        *,
        keep_workdir: bool,
    ) -> tuple:
        """Unzip + parse once + resolve a deep copy (resolution mutates).
        The stored definition is the unresolved parse (secrets stay
        placeholders in the document, as in the reference). When the app
        ships a ``python/`` dir and ``keep_workdir`` is set, the extracted
        tree is kept under the service's work root so the executor can
        import user agent code after the temp dir is gone."""
        with tempfile.TemporaryDirectory(prefix="langstream-app-") as tmp:
            app_dir = os.path.join(tmp, "app")
            os.makedirs(app_dir)
            with zipfile.ZipFile(io.BytesIO(archive)) as zf:
                for member in zf.namelist():
                    target = os.path.normpath(os.path.join(app_dir, member))
                    if not target.startswith(app_dir + os.sep):
                        raise ValueError(f"archive escapes app dir: {member}")
                zf.extractall(app_dir)
            instance_file = secrets_file = None
            if instance_yaml:
                instance_file = os.path.join(tmp, "instance.yaml")
                with open(instance_file, "w") as f:
                    f.write(instance_yaml)
            if secrets_yaml:
                secrets_file = os.path.join(tmp, "secrets.yaml")
                with open(secrets_file, "w") as f:
                    f.write(secrets_yaml)
            checksum = application_checksum(app_dir)
            raw = parse_application_directory(
                app_dir, instance_file=instance_file, secrets_file=secrets_file
            )
            application = resolve_placeholders(copy.deepcopy(raw))
            if application.python_path and keep_workdir:
                workdir = self._workdir(tenant, application_id)
                shutil.rmtree(workdir, ignore_errors=True)
                shutil.copytree(application.python_path, workdir)
                application.python_path = workdir
                raw.python_path = workdir
            # validation: the plan must build (implicit topics, agent
            # types, gateway topic references)
            build_execution_plan(application)
            definition = dataclasses.asdict(raw)
            secrets = definition.pop("secrets", {})
            instance = definition.pop("instance", {})
            return application, definition, instance, secrets, checksum

    def _workdir(self, tenant: str, application_id: str) -> str:
        if self._work_root is None:
            self._work_root = tempfile.mkdtemp(prefix="langstream-cp-")
        return os.path.join(self._work_root, tenant, application_id, "python")

    # -- lifecycle --------------------------------------------------- #
    async def deploy(
        self,
        tenant: str,
        application_id: str,
        archive: bytes,
        instance_yaml: Optional[str] = None,
        secrets_yaml: Optional[str] = None,
        *,
        update: bool = False,
        dry_run: bool = False,
    ) -> StoredApplication:
        self.tenants.get(tenant)  # raises TenantNotFound
        existing = self.store.get(tenant, application_id)
        if existing is not None and not update:
            raise ApplicationAlreadyExists(application_id)
        if existing is None and update:
            raise ApplicationNotFound(application_id)

        application, definition, instance, secrets, checksum = (
            self._materialize(
                tenant, application_id, archive, instance_yaml, secrets_yaml,
                keep_workdir=not dry_run,
            )
        )
        application.application_id = application_id
        application.tenant = tenant

        units = application_resource_units(application)
        current = sum(
            application_resource_units(self._stored_to_application(app))
            for app in self.store.list(tenant)
            if app.application_id != application_id
        )
        self.tenants.check_resource_limit(tenant, units, current)

        if dry_run:
            return StoredApplication(
                application_id=application_id, tenant=tenant,
                definition=definition, instance=instance, secrets={},
                checksum=checksum, status="VALIDATED",
            )

        code_id = self.code_storage.store(tenant, application_id, archive)
        previous_code_id = existing.code_archive_id if existing else None
        stored = StoredApplication(
            application_id=application_id, tenant=tenant,
            definition=definition, instance=instance, secrets=secrets,
            code_archive_id=code_id, checksum=checksum, status="DEPLOYING",
        )
        self.store.put(stored)
        try:
            await self.executor.deploy(stored, application)
            stored.status = "DEPLOYED"
            stored.status_detail = ""
        except Exception as err:  # noqa: BLE001 — status carries the error
            stored.status = "ERROR"
            stored.status_detail = f"{type(err).__name__}: {err}"
            self.store.put(stored)
            raise
        self.store.put(stored)
        # the update is live: the superseded archive version can go
        if previous_code_id and previous_code_id != code_id:
            self.code_storage.delete(tenant, previous_code_id)
        return stored

    async def delete(self, tenant: str, application_id: str) -> None:
        stored = self.store.get(tenant, application_id)
        if stored is None:
            raise ApplicationNotFound(application_id)
        stored.status = "DELETING"
        self.store.put(stored)
        await self.executor.delete(tenant, application_id)
        if stored.code_archive_id:
            self.code_storage.delete(tenant, stored.code_archive_id)
        self.store.delete(tenant, application_id)
        if self._work_root is not None:
            shutil.rmtree(
                os.path.join(self._work_root, tenant, application_id),
                ignore_errors=True,
            )

    def on_tenant_deleted(self, tenant: str) -> None:
        """Drop tenant-scoped leftovers (archives, workdirs, store docs)."""
        delete_tenant = getattr(self.code_storage, "delete_tenant", None)
        if delete_tenant is not None:
            delete_tenant(tenant)
        self.store.on_tenant_deleted(tenant)
        if self._work_root is not None:
            shutil.rmtree(
                os.path.join(self._work_root, tenant), ignore_errors=True
            )

    def get(self, tenant: str, application_id: str) -> StoredApplication:
        stored = self.store.get(tenant, application_id)
        if stored is None:
            raise ApplicationNotFound(application_id)
        return stored

    def list(self, tenant: str) -> List[StoredApplication]:
        self.tenants.get(tenant)
        return self.store.list(tenant)

    def download_code(self, tenant: str, application_id: str) -> bytes:
        stored = self.get(tenant, application_id)
        if not stored.code_archive_id:
            raise ApplicationNotFound(f"{application_id} has no code archive")
        return self.code_storage.download(tenant, stored.code_archive_id)

    def logs(self, tenant: str, application_id: str) -> List[str]:
        self.get(tenant, application_id)
        return self.executor.logs(tenant, application_id)

    # -- helpers ----------------------------------------------------- #
    def _stored_to_application(self, stored: StoredApplication) -> Application:
        """Rebuild enough of the Application model from a stored document
        to compute resource units (parallelism/size per agent)."""
        from langstream_tpu.model.application import (
            AgentConfiguration,
            Application,
            Module,
            Pipeline,
            ResourcesSpec,
        )

        app = Application(application_id=stored.application_id)
        for module_id, module_doc in (stored.definition.get("modules") or {}).items():
            module = Module(id=module_id)
            for pipeline_id, pipeline_doc in (module_doc.get("pipelines") or {}).items():
                pipeline = Pipeline(id=pipeline_id)
                for agent_doc in pipeline_doc.get("agents", []):
                    resources = agent_doc.get("resources") or {}
                    pipeline.agents.append(
                        AgentConfiguration(
                            type=agent_doc.get("type", ""),
                            id=agent_doc.get("id"),
                            resources=ResourcesSpec(
                                parallelism=resources.get("parallelism", 1),
                                size=resources.get("size", 1),
                            ),
                        )
                    )
                module.pipelines[pipeline_id] = pipeline
            app.modules[module_id] = module
        return app


def zip_directory(app_dir: str) -> bytes:
    """Zip an application directory (what the CLI does before upload)."""
    buffer = io.BytesIO()
    with zipfile.ZipFile(buffer, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(app_dir):
            for name in sorted(files):
                path = os.path.join(root, name)
                zf.write(path, os.path.relpath(path, app_dir))
    return buffer.getvalue()
