"""Code storage: versioned application archives.

Reference SPI: ``langstream-api/src/main/java/ai/langstream/api/codestorage/
CodeStorage.java:22`` (store/download/delete archives per tenant), with S3
and Azure implementations under ``langstream-k8s-storage/.../codestorage/``
and a local-disk one in ``langstream-core/.../LocalDiskCodeStorage.java``.

Archives are opaque bytes (a zip of the application directory). Each
upload gets a unique code-archive id; the store keeps every version so a
running deployment can still fetch the archive it was planned from while a
newer version rolls out.
"""

from __future__ import annotations

import os
import pathlib
import shutil
import uuid
from typing import Any, Dict, List, Optional, Protocol


class CodeArchiveNotFound(KeyError):
    pass


def _validate_ids(tenant: str, code_id: str) -> None:
    """Refuse path/key traversal: no separators, no '..' anywhere (a
    SUBSTRING check — filesystem-backed stores join these into paths)."""
    if (
        "/" in tenant or "/" in code_id
        or "\\" in tenant or "\\" in code_id
        or ".." in tenant or ".." in code_id
    ):
        raise ValueError(f"invalid tenant/code id {tenant!r}/{code_id!r}")


class CodeStorage(Protocol):
    def store(self, tenant: str, application_id: str, archive: bytes) -> str:
        """Store an archive, return its unique code-archive id."""
        ...

    def download(self, tenant: str, code_id: str) -> bytes:
        ...

    def delete(self, tenant: str, code_id: str) -> None:
        ...

    def list(self, tenant: str) -> List[str]:
        ...


class LocalDiskCodeStorage:
    """Archives on the local filesystem:
    ``<root>/<tenant>/<code_id>.zip``."""

    def __init__(self, root: str) -> None:
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, tenant: str, code_id: str) -> pathlib.Path:
        _validate_ids(tenant, code_id)
        return self.root / tenant / f"{code_id}.zip"

    def store(self, tenant: str, application_id: str, archive: bytes) -> str:
        code_id = f"{application_id}-{uuid.uuid4().hex[:12]}"
        path = self._path(tenant, code_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_bytes(archive)
        os.replace(tmp, path)
        return code_id

    def download(self, tenant: str, code_id: str) -> bytes:
        path = self._path(tenant, code_id)
        if not path.exists():
            raise CodeArchiveNotFound(f"{tenant}/{code_id}")
        return path.read_bytes()

    def delete(self, tenant: str, code_id: str) -> None:
        path = self._path(tenant, code_id)
        if path.exists():
            path.unlink()

    def delete_tenant(self, tenant: str) -> None:
        shutil.rmtree(self.root / tenant, ignore_errors=True)

    def list(self, tenant: str) -> List[str]:
        directory = self.root / tenant
        if not directory.is_dir():
            return []
        return sorted(p.stem for p in directory.glob("*.zip"))


class InMemoryCodeStorage:
    """Archive store for tests and the single-process runner."""

    def __init__(self) -> None:
        self._archives: Dict[str, Dict[str, bytes]] = {}

    def store(self, tenant: str, application_id: str, archive: bytes) -> str:
        code_id = f"{application_id}-{uuid.uuid4().hex[:12]}"
        self._archives.setdefault(tenant, {})[code_id] = archive
        return code_id

    def download(self, tenant: str, code_id: str) -> bytes:
        try:
            return self._archives[tenant][code_id]
        except KeyError:
            raise CodeArchiveNotFound(f"{tenant}/{code_id}") from None

    def delete(self, tenant: str, code_id: str) -> None:
        self._archives.get(tenant, {}).pop(code_id, None)

    def delete_tenant(self, tenant: str) -> None:
        self._archives.pop(tenant, None)

    def list(self, tenant: str) -> List[str]:
        return sorted(self._archives.get(tenant, {}))


class _ObjectStoreCodeStorage:
    """Shared sync facade for object-store-backed archives at
    ``<prefix>/<tenant>/<code_id>.zip``: a dedicated event-loop thread
    serves the async client, so the store works from both sync CLI paths
    (code-download) and inside async webservice handlers (where
    ``asyncio.run`` would be illegal). Subclasses provide the four async
    object ops."""

    def __init__(self, prefix: str, thread_name: str) -> None:
        import asyncio
        import threading

        self.prefix = prefix.strip("/")
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=thread_name, daemon=True
        )
        self._thread.start()

    # -- async object ops (subclass hooks) ------------------------------ #
    async def _put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    async def _get(self, key: str) -> bytes:
        raise NotImplementedError

    async def _delete(self, key: str) -> None:
        raise NotImplementedError

    async def _list(self, prefix: str) -> List[str]:
        """Object keys under ``prefix``."""
        raise NotImplementedError

    async def _close_client(self) -> None:
        raise NotImplementedError

    # -- CodeStorage surface --------------------------------------------- #
    def _run(self, coro):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(120)

    def _key(self, tenant: str, code_id: str) -> str:
        _validate_ids(tenant, code_id)
        return f"{self.prefix}/{tenant}/{code_id}.zip"

    def store(self, tenant: str, application_id: str, archive: bytes) -> str:
        code_id = f"{application_id}-{uuid.uuid4().hex[:12]}"
        self._run(self._put(self._key(tenant, code_id), archive))
        return code_id

    def download(self, tenant: str, code_id: str) -> bytes:
        try:
            return self._run(self._get(self._key(tenant, code_id)))
        except IOError as error:
            if "404" in str(error):
                raise CodeArchiveNotFound(f"{tenant}/{code_id}") from None
            raise

    def delete(self, tenant: str, code_id: str) -> None:
        self._run(self._delete(self._key(tenant, code_id)))

    def delete_tenant(self, tenant: str) -> None:
        for code_id in self.list(tenant):
            self.delete(tenant, code_id)

    def list(self, tenant: str) -> List[str]:
        keys = self._run(self._list(f"{self.prefix}/{tenant}/"))
        out = []
        for key in keys:
            name = key.rsplit("/", 1)[-1]
            if name.endswith(".zip"):
                out.append(name[:-4])
        return sorted(out)

    def close(self) -> None:
        self._run(self._close_client())
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)


class S3CodeStorage(_ObjectStoreCodeStorage):
    """S3-backed archives (reference: ``langstream-k8s-storage/.../
    codestorage/S3CodeStorage.java`` — bucket + endpoint + keys config
    shape kept compatible) over the SigV4 client from
    ``agents/storage.py``."""

    def __init__(
        self,
        *,
        bucket: str,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        prefix: str = "code",
    ) -> None:
        from langstream_tpu.agents.storage import S3Client

        super().__init__(prefix, "s3-codestorage")
        self.bucket = bucket
        self._client = S3Client(
            endpoint=endpoint, access_key=access_key,
            secret_key=secret_key, region=region,
        )

    async def _put(self, key: str, data: bytes) -> None:
        await self._client.put_object(self.bucket, key, data)

    async def _get(self, key: str) -> bytes:
        return await self._client.get_object(self.bucket, key)

    async def _delete(self, key: str) -> None:
        await self._client.delete_object(self.bucket, key)

    async def _list(self, prefix: str) -> List[str]:
        objects = await self._client.list_objects(self.bucket, prefix=prefix)
        return [obj["key"] for obj in objects]

    async def _close_client(self) -> None:
        await self._client.close()


class AzureBlobCodeStorage(_ObjectStoreCodeStorage):
    """Azure-backed archives (reference: ``langstream-k8s-storage/.../
    codestorage/AzureBlobCodeStorage.java``) over the native REST client
    (``agents/azure_blob.py``)."""

    def __init__(
        self,
        *,
        endpoint: str,
        container: str,
        account: Optional[str] = None,
        account_key: Optional[str] = None,
        sas_token: Optional[str] = None,
        prefix: str = "code",
    ) -> None:
        from langstream_tpu.agents.azure_blob import AzureBlobClient

        super().__init__(prefix, "azure-codestorage")
        self._client = AzureBlobClient(
            endpoint=endpoint, container=container, account=account,
            account_key=account_key, sas_token=sas_token,
        )

    async def _put(self, key: str, data: bytes) -> None:
        await self._client.put_blob(key, data)

    async def _get(self, key: str) -> bytes:
        return await self._client.get_blob(key)

    async def _delete(self, key: str) -> None:
        await self._client.delete_blob(key)

    async def _list(self, prefix: str) -> List[str]:
        blobs = await self._client.list_blobs(prefix=prefix)
        return [blob["name"] for blob in blobs]

    async def _close_client(self) -> None:
        await self._client.close()


def create_code_storage(config: Optional[Dict[str, Any]] = None) -> CodeStorage:
    """Factory keyed on ``type``: ``local-disk`` (default), ``memory``,
    ``s3`` (native SigV4 client), ``azure`` (native Shared Key/SAS REST
    client)."""
    config = config or {}
    kind = config.get("type", "local-disk")
    if kind in ("local-disk", "local"):
        root = config.get("path") or config.get("root")
        if not root:
            raise ValueError("local-disk code storage needs a 'path'")
        return LocalDiskCodeStorage(root)
    if kind in ("memory", "in-memory"):
        return InMemoryCodeStorage()
    if kind == "s3":
        bucket = config.get("bucket-name") or config.get("bucket")
        endpoint = config.get("endpoint")
        if not bucket or not endpoint:
            raise ValueError("s3 code storage needs 'bucket-name' and 'endpoint'")
        return S3CodeStorage(
            bucket=bucket,
            endpoint=endpoint,
            access_key=config.get("access-key", ""),
            secret_key=config.get("secret-key", ""),
            region=config.get("region", "us-east-1"),
            prefix=config.get("prefix", "code"),
        )
    if kind in ("azure", "azure-blob-storage"):
        endpoint = config.get("endpoint")
        account = config.get("storage-account-name")
        if not endpoint and account:
            endpoint = f"https://{account}.blob.core.windows.net"
        if not endpoint:
            raise ValueError(
                "azure code storage needs 'endpoint' or "
                "'storage-account-name'"
            )
        return AzureBlobCodeStorage(
            endpoint=endpoint,
            container=config.get("container", "langstream-code"),
            account=account,
            account_key=config.get("storage-account-key"),
            sas_token=config.get("sas-token"),
            prefix=config.get("prefix", "code"),
        )
    raise ValueError(f"unknown code storage type {kind!r}")
