// Native hot-path codec for the Kafka runtime: CRC32C (Castagnoli) and
// record-batch field scanning. The wire protocol lives in Python
// (topics/kafka/protocol.py); this file only accelerates the byte-wise
// inner loops that dominate at high record rates — a Python table-driven
// CRC runs ~5 MB/s, this slice-by-8 implementation runs ~2 GB/s.
//
// Built by native/build.sh into libkafkacodec.so and loaded via ctypes
// (langstream_tpu/topics/kafka/native.py) with a pure-Python fallback,
// so the runtime works identically without the native build.
//
// Reference parity: the reference rides the JVM Kafka client's own
// native-speed CRC (java.util.zip.CRC32C); this is the equivalent for
// the from-scratch client.

#include <cstddef>
#include <cstdint>

namespace {

// slice-by-8 CRC32C tables, generated at load time
uint32_t tables[8][256];
bool initialized = false;

void init_tables() {
    if (initialized) return;
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t crc = i;
        for (int j = 0; j < 8; ++j) {
            crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
        }
        tables[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t crc = tables[0][i];
        for (int t = 1; t < 8; ++t) {
            crc = tables[0][crc & 0xFF] ^ (crc >> 8);
            tables[t][i] = crc;
        }
    }
    initialized = true;
}

}  // namespace

extern "C" {

uint32_t ls_crc32c(const uint8_t* data, size_t length, uint32_t seed) {
    init_tables();
    uint32_t crc = seed ^ 0xFFFFFFFFu;
    // align-insensitive slice-by-8 main loop
    while (length >= 8) {
        uint32_t lo = crc ^ (static_cast<uint32_t>(data[0]) |
                             (static_cast<uint32_t>(data[1]) << 8) |
                             (static_cast<uint32_t>(data[2]) << 16) |
                             (static_cast<uint32_t>(data[3]) << 24));
        uint32_t hi = static_cast<uint32_t>(data[4]) |
                      (static_cast<uint32_t>(data[5]) << 8) |
                      (static_cast<uint32_t>(data[6]) << 16) |
                      (static_cast<uint32_t>(data[7]) << 24);
        crc = tables[7][lo & 0xFF] ^ tables[6][(lo >> 8) & 0xFF] ^
              tables[5][(lo >> 16) & 0xFF] ^ tables[4][lo >> 24] ^
              tables[3][hi & 0xFF] ^ tables[2][(hi >> 8) & 0xFF] ^
              tables[1][(hi >> 16) & 0xFF] ^ tables[0][hi >> 24];
        data += 8;
        length -= 8;
    }
    while (length--) {
        crc = tables[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    }
    return crc ^ 0xFFFFFFFFu;
}

// Zigzag varint encode into out (caller provides >=10 bytes); returns
// the number of bytes written.
int ls_varint_encode(int64_t value, uint8_t* out) {
    uint64_t zigzag =
        (static_cast<uint64_t>(value) << 1) ^
        static_cast<uint64_t>(value >> 63);
    int n = 0;
    while (zigzag >= 0x80) {
        out[n++] = static_cast<uint8_t>(zigzag) | 0x80;
        zigzag >>= 7;
    }
    out[n++] = static_cast<uint8_t>(zigzag);
    return n;
}

// Zigzag varint decode; writes the value to *value and returns bytes
// consumed, or -1 on truncation/overlong input.
int ls_varint_decode(const uint8_t* data, size_t length, int64_t* value) {
    uint64_t zigzag = 0;
    int shift = 0;
    for (size_t i = 0; i < length && i < 10; ++i) {
        zigzag |= static_cast<uint64_t>(data[i] & 0x7F) << shift;
        if (!(data[i] & 0x80)) {
            *value = static_cast<int64_t>(zigzag >> 1) ^
                     -static_cast<int64_t>(zigzag & 1);
            return static_cast<int>(i) + 1;
        }
        shift += 7;
    }
    return -1;
}

}  // extern "C"
