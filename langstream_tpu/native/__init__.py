"""Native (C++) components, compiled lazily with the system toolchain.

The reference ships native code only indirectly (DJL's JNI); this framework
uses a small C++ core for the durable log store (``logstore.cpp``) — the
role Kafka's log layer plays in the reference data plane. Binaries are
compiled once per source-hash into a cache directory and loaded with
``ctypes`` (pybind11 is not in this image).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import tempfile
import threading
from typing import Optional

_HERE = pathlib.Path(__file__).resolve().parent
_LOCK = threading.Lock()
_LIBS = {}


def _cache_dir() -> pathlib.Path:
    root = os.environ.get("LANGSTREAM_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "langstream_tpu"
    )
    path = pathlib.Path(root)
    path.mkdir(parents=True, exist_ok=True)
    return path


def build_library(
    source_name: str, extra_flags: Optional[list] = None
) -> Optional[pathlib.Path]:
    """Compile ``native/<source_name>`` into a cached .so; None on failure."""
    source = _HERE / source_name
    text = source.read_bytes()
    tag = hashlib.sha256(text).hexdigest()[:16]
    out = _cache_dir() / f"{source.stem}-{tag}.so"
    if out.exists():
        return out
    flags = ["-O2", "-shared", "-fPIC", "-std=c++17"] + (extra_flags or [])
    with tempfile.TemporaryDirectory() as tmp:
        tmp_out = pathlib.Path(tmp) / out.name
        cmd = ["g++", *flags, str(source), "-o", str(tmp_out), "-lz"]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, timeout=120
            )
        except (OSError, subprocess.SubprocessError):
            return None
        tmp_out.replace(out)
    return out


def load_logstore() -> Optional[ctypes.CDLL]:
    """Load (compiling if needed) the segmented log store library."""
    with _LOCK:
        if "logstore" in _LIBS:
            return _LIBS["logstore"]
        lib = None
        path = build_library("logstore.cpp")
        if path is not None:
            try:
                lib = ctypes.CDLL(str(path))
            except OSError:
                lib = None
        if lib is not None:
            lib.ls_open.restype = ctypes.c_void_p
            lib.ls_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.ls_append.restype = ctypes.c_int64
            lib.ls_append.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
            ]
            lib.ls_end_offset.restype = ctypes.c_int64
            lib.ls_end_offset.argtypes = [ctypes.c_void_p]
            lib.ls_base_offset.restype = ctypes.c_int64
            lib.ls_base_offset.argtypes = [ctypes.c_void_p]
            lib.ls_read_batch.restype = ctypes.c_int64
            lib.ls_read_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.ls_sync.restype = ctypes.c_int
            lib.ls_sync.argtypes = [ctypes.c_void_p]
            lib.ls_close.restype = None
            lib.ls_close.argtypes = [ctypes.c_void_p]
        _LIBS["logstore"] = lib
        return lib


def load_kafkacodec() -> Optional[ctypes.CDLL]:
    """Load (compiling if needed) the Kafka codec hot-path library."""
    with _LOCK:
        if "kafkacodec" in _LIBS:
            return _LIBS["kafkacodec"]
        lib = None
        path = build_library("kafkacodec.cpp")
        if path is not None:
            try:
                lib = ctypes.CDLL(str(path))
            except OSError:
                lib = None
        if lib is not None:
            lib.ls_crc32c.restype = ctypes.c_uint32
            lib.ls_crc32c.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
            ]
            lib.ls_varint_encode.restype = ctypes.c_int
            lib.ls_varint_encode.argtypes = [
                ctypes.c_int64, ctypes.c_char_p,
            ]
            lib.ls_varint_decode.restype = ctypes.c_int
            lib.ls_varint_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int64),
            ]
        _LIBS["kafkacodec"] = lib
        return lib
