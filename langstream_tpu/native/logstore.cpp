// Segmented append-only log store — the native core of the durable topic
// runtime ("tpulog"), playing the role Kafka's log layer plays for the
// reference's data plane (langstream-kafka-runtime/.../KafkaTopicConnectionsRuntime.java:53).
//
// One LogStore = one topic partition on disk:
//   <dir>/<base-offset, 20 digits>.log   frames: [u32 len][u32 crc32][payload]
//   <dir>/<base-offset, 20 digits>.idx   u64 little-endian file position per record
//
// The .idx file gives O(1) offset -> file-position lookup; recovery scans the
// last segment's tail and truncates torn writes (crc mismatch / short frame).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image). All calls are
// serialized per-handle with a mutex; the Python side holds one handle per
// partition.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

constexpr uint32_t kFrameHeader = 8;  // u32 len + u32 crc

struct Segment {
    int64_t base = 0;       // offset of the first record
    int64_t count = 0;      // records in this segment
    std::string log_path;
    std::string idx_path;
};

std::string offset_name(int64_t base, const char* ext) {
    char buf[40];
    std::snprintf(buf, sizeof(buf), "%020lld%s",
                  static_cast<long long>(base), ext);
    return std::string(buf);
}

int64_t file_size(const std::string& path) {
    struct stat st;
    if (stat(path.c_str(), &st) != 0) return -1;
    return st.st_size;
}

struct LogStore {
    std::string dir;
    uint64_t segment_bytes;
    std::vector<Segment> segments;
    // active segment write handles
    FILE* log_fp = nullptr;
    FILE* idx_fp = nullptr;
    int64_t active_log_size = 0;
    std::mutex mu;

    ~LogStore() {
        if (log_fp) fclose(log_fp);
        if (idx_fp) fclose(idx_fp);
    }
};

bool read_index_entry(FILE* fp, int64_t i, uint64_t* pos) {
    if (fseeko(fp, i * 8, SEEK_SET) != 0) return false;
    uint8_t buf[8];
    if (fread(buf, 1, 8, fp) != 8) return false;
    uint64_t v = 0;
    for (int b = 7; b >= 0; --b) v = (v << 8) | buf[b];
    *pos = v;
    return true;
}

void write_u32(uint8_t* out, uint32_t v) {
    out[0] = v & 0xff; out[1] = (v >> 8) & 0xff;
    out[2] = (v >> 16) & 0xff; out[3] = (v >> 24) & 0xff;
}

uint32_t read_u32(const uint8_t* in) {
    return (uint32_t)in[0] | ((uint32_t)in[1] << 8) |
           ((uint32_t)in[2] << 16) | ((uint32_t)in[3] << 24);
}

// Validate the tail of a segment against its index; truncate torn writes.
// Returns the number of valid records.
int64_t recover_segment(const Segment& seg) {
    int64_t isize = file_size(seg.idx_path);
    int64_t lsize = file_size(seg.log_path);
    if (isize < 0 || lsize < 0) return 0;
    int64_t n = isize / 8;
    FILE* ifp = fopen(seg.idx_path.c_str(), "rb");
    FILE* lfp = fopen(seg.log_path.c_str(), "rb");
    if (!ifp || !lfp) {
        if (ifp) fclose(ifp);
        if (lfp) fclose(lfp);
        return 0;
    }
    int64_t valid = 0;
    // Walk back from the end: most recovery cases only lose the last frame.
    for (int64_t i = n - 1; i >= 0; --i) {
        uint64_t pos;
        if (!read_index_entry(ifp, i, &pos)) continue;
        if ((int64_t)pos + kFrameHeader > lsize) continue;
        uint8_t hdr[kFrameHeader];
        if (fseeko(lfp, pos, SEEK_SET) != 0) continue;
        if (fread(hdr, 1, kFrameHeader, lfp) != kFrameHeader) continue;
        uint32_t len = read_u32(hdr);
        uint32_t crc = read_u32(hdr + 4);
        if ((int64_t)pos + kFrameHeader + len > lsize) continue;
        std::vector<uint8_t> payload(len);
        if (len && fread(payload.data(), 1, len, lfp) != len) continue;
        if ((uint32_t)crc32(0, payload.data(), len) != crc) continue;
        valid = i + 1;
        break;
    }
    fclose(ifp);
    fclose(lfp);
    return valid;
}

bool open_active(LogStore* s) {
    if (s->segments.empty()) {
        Segment seg;
        seg.base = 0;
        seg.log_path = s->dir + "/" + offset_name(0, ".log");
        seg.idx_path = s->dir + "/" + offset_name(0, ".idx");
        s->segments.push_back(seg);
    }
    Segment& seg = s->segments.back();
    s->log_fp = fopen(seg.log_path.c_str(), "ab");
    s->idx_fp = fopen(seg.idx_path.c_str(), "ab");
    if (!s->log_fp || !s->idx_fp) return false;
    // Truncate files to the recovered record count (drop torn tail bytes).
    int64_t valid = seg.count;
    FILE* ifp = fopen(seg.idx_path.c_str(), "rb");
    int64_t log_end = 0;
    if (valid > 0 && ifp) {
        uint64_t pos = 0;
        if (read_index_entry(ifp, valid - 1, &pos)) {
            FILE* lfp = fopen(seg.log_path.c_str(), "rb");
            if (lfp) {
                uint8_t hdr[kFrameHeader];
                if (fseeko(lfp, pos, SEEK_SET) == 0 &&
                    fread(hdr, 1, kFrameHeader, lfp) == kFrameHeader) {
                    log_end = pos + kFrameHeader + read_u32(hdr);
                }
                fclose(lfp);
            }
        }
    }
    if (ifp) fclose(ifp);
    if (truncate(seg.idx_path.c_str(), valid * 8) != 0 ||
        truncate(seg.log_path.c_str(), log_end) != 0) {
        return false;
    }
    // reopen after truncate so append positions are correct
    fclose(s->log_fp); fclose(s->idx_fp);
    s->log_fp = fopen(seg.log_path.c_str(), "ab");
    s->idx_fp = fopen(seg.idx_path.c_str(), "ab");
    s->active_log_size = log_end;
    return s->log_fp && s->idx_fp;
}

}  // namespace

extern "C" {

LogStore* ls_open(const char* dir, uint64_t segment_bytes) {
    LogStore* s = new LogStore();
    s->dir = dir;
    s->segment_bytes = segment_bytes ? segment_bytes : (64ull << 20);
    mkdir(dir, 0777);  // EEXIST is fine
    DIR* d = opendir(dir);
    if (!d) { delete s; return nullptr; }
    std::vector<int64_t> bases;
    while (dirent* e = readdir(d)) {
        std::string name = e->d_name;
        if (name.size() == 24 && name.substr(20) == ".log") {
            bases.push_back(strtoll(name.substr(0, 20).c_str(), nullptr, 10));
        }
    }
    closedir(d);
    std::sort(bases.begin(), bases.end());
    for (int64_t base : bases) {
        Segment seg;
        seg.base = base;
        seg.log_path = s->dir + "/" + offset_name(base, ".log");
        seg.idx_path = s->dir + "/" + offset_name(base, ".idx");
        seg.count = recover_segment(seg);
        s->segments.push_back(seg);
    }
    if (!open_active(s)) { delete s; return nullptr; }
    return s;
}

int64_t ls_append(LogStore* s, const uint8_t* payload, uint32_t len) {
    std::lock_guard<std::mutex> lock(s->mu);
    Segment* seg = &s->segments.back();
    if (s->active_log_size > 0 &&
        (uint64_t)s->active_log_size + kFrameHeader + len > s->segment_bytes) {
        // roll a new segment
        fclose(s->log_fp); fclose(s->idx_fp);
        Segment next;
        next.base = seg->base + seg->count;
        next.log_path = s->dir + "/" + offset_name(next.base, ".log");
        next.idx_path = s->dir + "/" + offset_name(next.base, ".idx");
        s->segments.push_back(next);
        seg = &s->segments.back();
        s->log_fp = fopen(seg->log_path.c_str(), "ab");
        s->idx_fp = fopen(seg->idx_path.c_str(), "ab");
        s->active_log_size = 0;
        if (!s->log_fp || !s->idx_fp) return -1;
    }
    uint64_t pos = (uint64_t)s->active_log_size;
    uint8_t hdr[kFrameHeader];
    write_u32(hdr, len);
    write_u32(hdr + 4, (uint32_t)crc32(0, payload, len));
    if (fwrite(hdr, 1, kFrameHeader, s->log_fp) != kFrameHeader) return -1;
    if (len && fwrite(payload, 1, len, s->log_fp) != len) return -1;
    uint8_t ibuf[8];
    for (int b = 0; b < 8; ++b) ibuf[b] = (pos >> (8 * b)) & 0xff;
    if (fwrite(ibuf, 1, 8, s->idx_fp) != 8) return -1;
    fflush(s->log_fp);
    fflush(s->idx_fp);
    s->active_log_size += kFrameHeader + len;
    seg->count += 1;
    return seg->base + seg->count - 1;
}

int64_t ls_end_offset(LogStore* s) {
    std::lock_guard<std::mutex> lock(s->mu);
    const Segment& seg = s->segments.back();
    return seg.base + seg.count;
}

int64_t ls_base_offset(LogStore* s) {
    std::lock_guard<std::mutex> lock(s->mu);
    return s->segments.front().base;
}

// Read up to max_records frames starting at `offset` into `buf` as
// [u32 len][payload]... Returns the number of records read, writes the
// total bytes used to *bytes_out. Returns -2 if the first record alone
// does not fit in buflen (caller should grow the buffer).
int64_t ls_read_batch(LogStore* s, int64_t offset, int64_t max_records,
                      uint8_t* buf, uint64_t buflen, uint64_t* bytes_out) {
    std::lock_guard<std::mutex> lock(s->mu);
    *bytes_out = 0;
    if (s->segments.empty()) return 0;
    // fsync-less readers: flush writer buffers so reads see appended data
    if (s->log_fp) fflush(s->log_fp);
    if (s->idx_fp) fflush(s->idx_fp);
    int64_t n_read = 0;
    uint64_t used = 0;
    while (n_read < max_records) {
        // locate segment containing `offset`
        const Segment* seg = nullptr;
        for (auto it = s->segments.rbegin(); it != s->segments.rend(); ++it) {
            if (it->base <= offset) { seg = &*it; break; }
        }
        if (!seg || offset >= seg->base + seg->count) break;
        FILE* ifp = fopen(seg->idx_path.c_str(), "rb");
        FILE* lfp = fopen(seg->log_path.c_str(), "rb");
        if (!ifp || !lfp) {
            if (ifp) fclose(ifp);
            if (lfp) fclose(lfp);
            break;
        }
        bool progressed = false;
        while (n_read < max_records && offset < seg->base + seg->count) {
            uint64_t pos;
            if (!read_index_entry(ifp, offset - seg->base, &pos)) break;
            uint8_t hdr[kFrameHeader];
            if (fseeko(lfp, pos, SEEK_SET) != 0) break;
            if (fread(hdr, 1, kFrameHeader, lfp) != kFrameHeader) break;
            uint32_t len = read_u32(hdr);
            if (used + 4 + len > buflen) {
                fclose(ifp); fclose(lfp);
                if (n_read == 0) return -2;
                *bytes_out = used;
                return n_read;
            }
            write_u32(buf + used, len);
            if (len && fread(buf + used + 4, 1, len, lfp) != len) break;
            used += 4 + len;
            offset += 1;
            n_read += 1;
            progressed = true;
        }
        fclose(ifp);
        fclose(lfp);
        if (!progressed) break;
    }
    *bytes_out = used;
    return n_read;
}

int ls_sync(LogStore* s) {
    std::lock_guard<std::mutex> lock(s->mu);
    if (fflush(s->log_fp) != 0) return -1;
    if (fflush(s->idx_fp) != 0) return -1;
    if (fsync(fileno(s->log_fp)) != 0) return -1;
    if (fsync(fileno(s->idx_fp)) != 0) return -1;
    return 0;
}

void ls_close(LogStore* s) {
    delete s;
}

}  // extern "C"
