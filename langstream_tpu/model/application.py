"""In-memory model of a parsed application.

Equivalent of the reference's model classes
(``langstream-api/src/main/java/ai/langstream/api/model/Application.java:26``,
``Module.java:13-21``, ``Pipeline.java:22``, ``AgentConfiguration.java:8-18``,
``TopicDefinition.java:30``, ``Gateway.java:31``, ``ResourcesSpec.java:22``):
an application is resources + modules (each with pipelines and topics) +
gateways + secrets + the instance (clusters and globals).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from langstream_tpu.api.errors import ErrorsSpec

DEFAULT_MODULE = "default"


@dataclasses.dataclass
class ResourcesSpec:
    """Replica count + per-replica compute units + disk
    (``ResourcesSpec.java:22``, ``DiskSpec.java``).

    In the TPU build ``parallelism`` remains "data parallelism by
    replication" (consumer-group sharding), while ``size`` maps to TPU
    topology requests (e.g. chips per replica) instead of cpu/mem units.
    """

    parallelism: int = 1
    size: int = 1
    disk: Optional[Dict[str, Any]] = None

    @classmethod
    def from_config(cls, config: Optional[Dict[str, Any]]) -> "ResourcesSpec":
        if not config:
            return cls()
        return cls(
            parallelism=int(config.get("parallelism", 1)),
            size=int(config.get("size", 1)),
            disk=config.get("disk"),
        )


@dataclasses.dataclass
class TopicDefinition:
    name: str
    creation_mode: str = "none"  # "create-if-not-exists" | "none"
    deletion_mode: str = "none"
    partitions: int = 1
    keep_alive: bool = False
    schema: Optional[Dict[str, Any]] = None
    options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    implicit: bool = False

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "TopicDefinition":
        return cls(
            name=config["name"],
            creation_mode=config.get("creation-mode", "none"),
            deletion_mode=config.get("deletion-mode", "none"),
            partitions=int(config.get("partitions", 1)),
            schema=config.get("schema"),
            options=config.get("options", {}) or {},
            config=config.get("config", {}) or {},
        )


@dataclasses.dataclass
class AgentConfiguration:
    """One step of a pipeline (``AgentConfiguration.java:8-18``)."""

    type: str
    id: Optional[str] = None
    name: Optional[str] = None
    input: Optional[str] = None
    output: Optional[str] = None
    configuration: Dict[str, Any] = dataclasses.field(default_factory=dict)
    resources: ResourcesSpec = dataclasses.field(default_factory=ResourcesSpec)
    errors: ErrorsSpec = dataclasses.field(default_factory=ErrorsSpec)

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "AgentConfiguration":
        if "type" not in config:
            raise ValueError(f"pipeline agent missing 'type': {config}")
        return cls(
            type=config["type"],
            id=config.get("id"),
            name=config.get("name"),
            input=config.get("input"),
            output=config.get("output"),
            configuration=config.get("configuration", {}) or {},
            resources=ResourcesSpec.from_config(config.get("resources")),
            errors=ErrorsSpec.from_config(config.get("errors")),
        )


@dataclasses.dataclass
class Pipeline:
    id: str
    module: str = DEFAULT_MODULE
    name: Optional[str] = None
    agents: List[AgentConfiguration] = dataclasses.field(default_factory=list)
    errors: ErrorsSpec = dataclasses.field(default_factory=ErrorsSpec)


@dataclasses.dataclass
class AssetDefinition:
    """Infrastructure an app needs provisioned before it runs — tables,
    collections, indexes (``langstream-api/.../model/AssetDefinition``;
    managers under ``langstream-core/.../impl/assets/``)."""

    id: str
    name: str
    asset_type: str
    creation_mode: str = "none"        # none | create-if-not-exists
    deletion_mode: str = "none"        # none | delete
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "AssetDefinition":
        return cls(
            id=config.get("id") or config.get("name"),
            name=config.get("name") or config.get("id"),
            asset_type=config.get("asset-type") or config.get("type"),
            creation_mode=config.get("creation-mode", "none"),
            deletion_mode=config.get("deletion-mode", "none"),
            config=config.get("config", {}) or {},
        )


@dataclasses.dataclass
class Module:
    id: str = DEFAULT_MODULE
    pipelines: Dict[str, Pipeline] = dataclasses.field(default_factory=dict)
    topics: Dict[str, TopicDefinition] = dataclasses.field(default_factory=dict)
    assets: Dict[str, AssetDefinition] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Gateway:
    """Gateway endpoint (``Gateway.java:31``; types produce / consume /
    chat / service, lines 39-44)."""

    id: str
    type: str
    topic: Optional[str] = None
    parameters: List[str] = dataclasses.field(default_factory=list)
    authentication: Optional[Dict[str, Any]] = None
    produce_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    consume_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    chat_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    service_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    events_topic: Optional[str] = None

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "Gateway":
        return cls(
            id=config["id"],
            type=config["type"],
            topic=config.get("topic"),
            parameters=config.get("parameters", []) or [],
            authentication=config.get("authentication"),
            produce_options=config.get("produce-options", {}) or {},
            consume_options=config.get("consume-options", {}) or {},
            chat_options=config.get("chat-options", {}) or {},
            service_options=config.get("service-options", {}) or {},
            events_topic=config.get("events-topic"),
        )


@dataclasses.dataclass
class Instance:
    """``instance.yaml``: clusters + globals
    (``examples/instances/kafka-kubernetes.yaml:18-23``)."""

    streaming_cluster: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"type": "memory"}
    )
    compute_cluster: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"type": "local"}
    )
    globals_: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "Instance":
        return cls(
            streaming_cluster=config.get("streamingCluster", {"type": "memory"}),
            compute_cluster=config.get("computeCluster", {"type": "local"}),
            globals_=config.get("globals", {}) or {},
        )


@dataclasses.dataclass
class Secrets:
    secrets: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Application:
    application_id: str = "app"
    tenant: str = "default"
    resources: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    modules: Dict[str, Module] = dataclasses.field(default_factory=dict)
    gateways: List[Gateway] = dataclasses.field(default_factory=list)
    instance: Instance = dataclasses.field(default_factory=Instance)
    secrets: Secrets = dataclasses.field(default_factory=Secrets)
    dependencies: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # path of the app's `python/` dir with user agent code (put on sys.path
    # at run; the reference mounts it into the gRPC runtime's PYTHONPATH)
    python_path: Optional[str] = None

    def module(self, module_id: str = DEFAULT_MODULE) -> Module:
        module = self.modules.get(module_id)
        if module is None:
            module = Module(id=module_id)
            self.modules[module_id] = module
        return module

    def all_topics(self) -> Dict[str, TopicDefinition]:
        out: Dict[str, TopicDefinition] = {}
        for module in self.modules.values():
            out.update(module.topics)
        return out

    @classmethod
    def from_document(
        cls,
        definition: Dict[str, Any],
        instance: Optional[Dict[str, Any]] = None,
        secrets: Optional[Dict[str, Any]] = None,
    ) -> "Application":
        """Rebuild an Application from its ``dataclasses.asdict`` document
        (the form the control plane stores and ships in CRs). Inverse of
        ``asdict`` for the snake_case field names used there."""
        app = cls(
            application_id=definition.get("application_id", "app"),
            tenant=definition.get("tenant", "default"),
            resources=definition.get("resources", {}) or {},
            dependencies=definition.get("dependencies", []) or [],
            python_path=definition.get("python_path"),
        )
        for module_id, module_doc in (definition.get("modules") or {}).items():
            module = Module(id=module_id)
            for name, topic_doc in (module_doc.get("topics") or {}).items():
                module.topics[name] = TopicDefinition(
                    name=topic_doc.get("name", name),
                    creation_mode=topic_doc.get("creation_mode", "none"),
                    deletion_mode=topic_doc.get("deletion_mode", "none"),
                    partitions=topic_doc.get("partitions", 1),
                    keep_alive=topic_doc.get("keep_alive", False),
                    schema=topic_doc.get("schema"),
                    options=topic_doc.get("options", {}) or {},
                    config=topic_doc.get("config", {}) or {},
                    implicit=topic_doc.get("implicit", False),
                )
            for pipeline_id, pipe_doc in (module_doc.get("pipelines") or {}).items():
                pipeline = Pipeline(
                    id=pipeline_id,
                    module=pipe_doc.get("module", module_id),
                    name=pipe_doc.get("name"),
                    errors=ErrorsSpec.from_config(pipe_doc.get("errors")),
                )
                for agent_doc in pipe_doc.get("agents", []) or []:
                    pipeline.agents.append(AgentConfiguration(
                        type=agent_doc["type"],
                        id=agent_doc.get("id"),
                        name=agent_doc.get("name"),
                        input=agent_doc.get("input"),
                        output=agent_doc.get("output"),
                        configuration=agent_doc.get("configuration", {}) or {},
                        resources=ResourcesSpec.from_config(
                            agent_doc.get("resources")
                        ),
                        errors=ErrorsSpec.from_config(agent_doc.get("errors")),
                    ))
                module.pipelines[pipeline_id] = pipeline
            app.modules[module_id] = module
        for gw_doc in definition.get("gateways", []) or []:
            app.gateways.append(Gateway(
                id=gw_doc["id"],
                type=gw_doc["type"],
                topic=gw_doc.get("topic"),
                parameters=gw_doc.get("parameters", []) or [],
                authentication=gw_doc.get("authentication"),
                produce_options=gw_doc.get("produce_options", {}) or {},
                consume_options=gw_doc.get("consume_options", {}) or {},
                chat_options=gw_doc.get("chat_options", {}) or {},
                service_options=gw_doc.get("service_options", {}) or {},
                events_topic=gw_doc.get("events_topic"),
            ))
        if instance is not None:
            app.instance = Instance(
                streaming_cluster=instance.get("streaming_cluster")
                or instance.get("streamingCluster") or {"type": "memory"},
                compute_cluster=instance.get("compute_cluster")
                or instance.get("computeCluster") or {"type": "local"},
                globals_=instance.get("globals_")
                or instance.get("globals") or {},
            )
        if secrets is not None:
            # only the wrapped asdict(Secrets) form — {"secrets": {...}};
            # guessing at unwrapped mappings could silently drop entries
            app.secrets = Secrets(secrets=secrets.get("secrets") or {})
        return app
