"""The application model — parsed YAML, pre-planning.

Equivalent of ``langstream-api/src/main/java/ai/langstream/api/model/``.
"""

from langstream_tpu.model.application import (
    AgentConfiguration,
    Application,
    Gateway,
    Instance,
    Module,
    Pipeline,
    ResourcesSpec,
    Secrets,
    TopicDefinition,
)

__all__ = [
    "AgentConfiguration",
    "Application",
    "Gateway",
    "Instance",
    "Module",
    "Pipeline",
    "ResourcesSpec",
    "Secrets",
    "TopicDefinition",
]
