"""Typed agent-configuration model: docs + validation.

The TPU-native counterpart of the reference's annotation-driven config
system (``@AgentConfig``/``@ConfigProperty`` doc model,
langstream-api/src/main/java/ai/langstream/api/doc/ConfigProperty.java,
validated reflectively by
langstream-core/src/main/java/ai/langstream/impl/uti/ClassConfigValidator.java:60
and surfaced as JSON for CLI docs). Here the declarations are plain
dataclasses in one table — no reflection — consumed by:

- the **compiler** (``compiler.planner``) to reject bad agent configs at
  plan time with actionable errors, and
- the **CLI** ``docs`` command to print per-agent reference docs (JSON
  or text).

Validation is deliberately advisory-strict: unknown keys are errors for
documented agents (matching ClassConfigValidator's default), but agent
types with no doc entry pass through untouched (custom/python agents).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

def _intish(v: Any) -> bool:
    # numeric STRINGS pass: placeholder defaults (`${globals.x:-256}`)
    # always substitute as strings, and the reference's Jackson-backed
    # validation (ClassConfigValidator.java:60) coerces them the same way
    if isinstance(v, str):
        try:
            int(v)
            return True
        except ValueError:
            return False
    return isinstance(v, int) and not isinstance(v, bool)


def _numberish(v: Any) -> bool:
    if isinstance(v, str):
        try:
            float(v)
            return True
        except ValueError:
            return False
    return isinstance(v, (int, float)) and not isinstance(v, bool)


_TYPE_CHECKS = {
    "string": lambda v: isinstance(v, str),
    # the empty string is NOT a boolean: a blank placeholder
    # substitution must fall back to the property default at the
    # validation layer, not pass through with ambiguous truthiness
    "boolean": lambda v: isinstance(v, bool) or (
        isinstance(v, str) and v.lower() in ("true", "false", "1", "0")
    ),
    "integer": _intish,
    "number": _numberish,
    "object": lambda v: isinstance(v, dict),
    "list": lambda v: isinstance(v, (list, tuple)),
    "any": lambda v: True,
}


@dataclasses.dataclass(frozen=True)
class ConfigProperty:
    name: str
    type: str = "string"           # string|boolean|integer|number|object|list|any
    description: str = ""
    required: bool = False
    default: Any = None
    choices: Optional[Tuple[str, ...]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name,
            "type": self.type,
            "description": self.description,
            "required": self.required,
        }
        if self.default is not None:
            out["default"] = self.default
        if self.choices:
            out["choices"] = list(self.choices)
        return out


@dataclasses.dataclass(frozen=True)
class AgentDoc:
    agent_type: str
    description: str
    properties: Tuple[ConfigProperty, ...] = ()
    category: str = "processor"    # source|processor|sink|service
    allow_unknown: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.agent_type,
            "category": self.category,
            "description": self.description,
            "properties": [p.to_dict() for p in self.properties],
        }


_P = ConfigProperty
_DOCS: Dict[str, AgentDoc] = {}


def register_doc(doc: AgentDoc) -> None:
    _DOCS[doc.agent_type] = doc


def get_doc(agent_type: str) -> Optional[AgentDoc]:
    return _DOCS.get(agent_type)


def all_docs() -> Dict[str, AgentDoc]:
    return dict(_DOCS)


def generate_docs_model() -> Dict[str, Any]:
    """Full JSON doc model (reference: the CLI's agent doc JSON)."""
    return {name: doc.to_dict() for name, doc in sorted(_DOCS.items())}


def validate_agent_config(
    agent_type: str, configuration: Dict[str, Any]
) -> List[str]:
    """Return a list of human-actionable errors ([] = valid). Unknown
    agent types validate as OK (custom agents document themselves)."""
    doc = _DOCS.get(agent_type)
    if doc is None:
        return []
    errors: List[str] = []
    by_name = {p.name: p for p in doc.properties}
    for prop in doc.properties:
        if prop.required and configuration.get(prop.name) is None:
            errors.append(
                f"{agent_type}: missing required property '{prop.name}'"
            )
    for key, value in (configuration or {}).items():
        prop = by_name.get(key)
        if prop is None:
            if not doc.allow_unknown:
                known = ", ".join(sorted(by_name)) or "(none)"
                errors.append(
                    f"{agent_type}: unknown property '{key}' "
                    f"(known: {known})"
                )
            continue
        if value is None:
            continue
        if value == "" and prop.type != "string":
            # "" is not a valid boolean/number/list literal (ADVICE r4),
            # and consumers read `config.get(key, default)` — a PRESENT
            # blank key would bypass the default and crash (int(""))
            # or silently flip (bool("")) at runtime. Fail at plan time
            # with the fix spelled out.
            errors.append(
                f"{agent_type}: property '{key}' is blank "
                f"(placeholder substituted to \"\") but expects "
                f"{prop.type} — give the placeholder a non-blank "
                f"default (`${{globals.x:-42}}`) or omit the key"
            )
            continue
        check = _TYPE_CHECKS.get(prop.type, _TYPE_CHECKS["any"])
        if not check(value):
            errors.append(
                f"{agent_type}: property '{key}' expects {prop.type}, "
                f"got {type(value).__name__}"
            )
        if prop.choices and isinstance(value, str) and value not in prop.choices:
            errors.append(
                f"{agent_type}: property '{key}' must be one of "
                f"{list(prop.choices)}, got {value!r}"
            )
    return errors


# ---------------------------------------------------------------------- #
# built-in agent docs
# ---------------------------------------------------------------------- #
_WHEN = _P("when", "string", "JSTL-style predicate; the step runs only on matching records")

for doc in [
    # --- GenAI toolkit steps (reference: GenAIToolKitFunctionAgentProvider
    # STEP_TYPES, impl/agents/ai/GenAIToolKitFunctionAgentProvider.java:53-74)
    AgentDoc("drop-fields", "Drop fields from the record", (
        _P("fields", "list", "field names to drop", required=True),
        _P("part", "string", "restrict to 'key' or 'value'", choices=("key", "value")),
        _WHEN,
    )),
    AgentDoc("merge-key-value", "Merge the key fields into the value", (_WHEN,)),
    AgentDoc("unwrap-key-value", "Replace the record with its key or value", (
        _P("unwrapKey", "boolean", "unwrap the key instead of the value", default=False),
        _WHEN,
    )),
    AgentDoc("cast", "Cast key/value to a schema type", (
        _P("schema-type", "string", "target schema type", required=True),
        _P("part", "string", "'key' or 'value'", choices=("key", "value")),
        _WHEN,
    )),
    AgentDoc("flatten", "Flatten nested structures into top-level fields", (
        _P("delimiter", "string", "separator for flattened names", default="_"),
        _P("part", "string", "'key' or 'value'", choices=("key", "value")),
        _WHEN,
    )),
    AgentDoc("drop", "Drop the whole record", (_WHEN,)),
    AgentDoc("compute", "Compute new fields with expressions", (
        _P("fields", "list", "list of {name, expression, type, optional}", required=True),
        _WHEN,
    )),
    AgentDoc("compute-ai-embeddings", "Compute embeddings for a text field", (
        _P("model", "string", "embeddings model name or checkpoint path"),
        _P("text", "string", "template of the text to embed", required=True),
        _P("embeddings-field", "string", "output field", required=True),
        _P("batch-size", "integer", "micro-batch size", default=10),
        _P("flush-interval", "integer", "max ms to hold a partial batch", default=0),
        _P("concurrency", "integer", "parallel in-flight batches", default=4),
        _P("ai-service", "string", "resource name of the AI service"),
        _WHEN,
    )),
    AgentDoc("query", "Query a datasource into a field", (
        _P("datasource", "string", "datasource resource name", required=True),
        _P("query", "string", "SQL/query with ? placeholders", required=True),
        _P("fields", "list", "expressions bound to the placeholders"),
        _P("output-field", "string", "where results land", required=True),
        _P("only-first", "boolean", "unwrap single row", default=False),
        _P("mode", "string", "query returns rows, execute mutates",
           default="query", choices=("query", "execute")),
        _WHEN,
    )),
    AgentDoc("ai-chat-completions", "Chat completion via the configured model service", (
        _P("model", "string", "model name"),
        _P("messages", "list", "chat messages with mustache templates", required=True),
        _P("completion-field", "string", "output field for the final text"),
        _P("log-field", "string", "field for the rendered prompt"),
        _P("stream-to-topic", "string", "topic for streamed chunks"),
        _P("stream-response-completion-field", "string", "field in streamed records"),
        _P("min-chunks-per-message", "integer", "chunk batching ramp", default=20),
        _P("temperature", "number", "sampling temperature"),
        _P("max-tokens", "integer", "max new tokens"),
        _P("top-p", "number", "nucleus sampling"),
        _P("top-k", "integer", "top-k sampling"),
        _P("stop", "list", "stop strings: generation ends at the first match"),
        _P("presence-penalty", "number", "flat logit penalty on generated tokens"),
        _P("frequency-penalty", "number", "per-count logit penalty on generated tokens"),
        _P("seed", "integer", "per-request sampling seed (reproducible sampling)"),
        _P("logit-bias", "object", "token id -> additive logit adjustment"),
        _P("session-field", "string", "expression for KV-cache session affinity"),
        _P("ai-service", "string", "resource name of the AI service"),
        _P("logprobs", "boolean", "emit per-token text + logprobs", default=False),
        _P("logprobs-field", "string", "field for token logprobs", default="value.logprobs"),
        _P("tokens-field", "string", "field for token text pieces", default="value.tokens"),
        _WHEN,
    )),
    AgentDoc("ai-text-completions", "Raw text completion via the configured model", (
        _P("model", "string", "model name"),
        _P("prompt", "list", "prompt template(s)", required=True),
        _P("completion-field", "string", "output field"),
        _P("log-field", "string", "field for the rendered prompt"),
        _P("stream-to-topic", "string", "topic for streamed chunks"),
        _P("stream-response-completion-field", "string", "field in streamed records"),
        _P("min-chunks-per-message", "integer", "chunk batching ramp", default=20),
        _P("temperature", "number", "sampling temperature"),
        _P("max-tokens", "integer", "max new tokens"),
        _P("top-p", "number", "nucleus sampling"),
        _P("top-k", "integer", "top-k sampling"),
        _P("stop", "list", "stop strings: generation ends at the first match"),
        _P("presence-penalty", "number", "flat logit penalty on generated tokens"),
        _P("frequency-penalty", "number", "per-count logit penalty on generated tokens"),
        _P("seed", "integer", "per-request sampling seed (reproducible sampling)"),
        _P("logit-bias", "object", "token id -> additive logit adjustment"),
        _P("ai-service", "string", "resource name of the AI service"),
        _P("logprobs", "boolean", "emit per-token text + logprobs", default=False),
        _P("logprobs-field", "string", "field for token logprobs", default="value.logprobs"),
        _P("tokens-field", "string", "field for token text pieces", default="value.tokens"),
        _WHEN,
    )),
    # --- text processing (reference: langstream-agents-text-processing)
    AgentDoc("text-extractor", "Extract plain text from documents", (_WHEN,)),
    AgentDoc("text-normaliser", "Normalise text (case, whitespace)", (
        _P("make-lowercase", "boolean", "lowercase the text", default=True),
        _P("trim-spaces", "boolean", "collapse whitespace", default=True),
    )),
    AgentDoc("text-splitter", "Split text into chunks for embeddings", (
        _P("splitter_type", "string", "splitting algorithm", default="RecursiveCharacterTextSplitter"),
        _P("separators", "list", "split separators in priority order"),
        _P("chunk_size", "integer", "max chunk length", default=200),
        _P("chunk_overlap", "integer", "overlap between chunks", default=100),
        _P("keep_separator", "boolean", "keep the separator text", default=False),
        _P("length_function", "string", "cl100k_base or a python len fn", default="cl100k_base"),
    )),
    AgentDoc("language-detector", "Detect the text language into a field", (
        _P("property", "string", "output property name", default="language"),
        _P("allowedLanguages", "list", "drop records outside this set"),
    )),
    AgentDoc("document-to-json", "Wrap raw text into a JSON document", (
        _P("text-field", "string", "field name for the text", default="text"),
        _P("copy-properties", "boolean", "copy record headers", default=True),
    )),
    # --- flow control (reference: langstream-agents-flow-control)
    AgentDoc("dispatch", "Route records to topics by condition", (
        _P("routes", "list", "list of {when, destination, action}", required=True),
    )),
    AgentDoc("timer-source", "Emit a record every interval", (
        _P("period-seconds", "integer", "emission period", default=60),
        _P("fields", "list", "computed fields for the emitted record"),
    ), category="source"),
    AgentDoc("trigger-event", "Emit an event record when a condition holds", (
        _P("destination", "string", "topic to send the event to"),
        _P("when", "string", "trigger condition", default="true"),
        _P("fields", "list", "computed fields of the event"),
        _P("continue-processing", "boolean", "also forward the original", default=True),
    )),
    AgentDoc("log-event", "Log matching records (debugging)", (
        _P("when", "string", "condition", default="true"),
        _P("message", "string", "log line prefix", default="log-event"),
        _P("fields", "list", "computed fields to log"),
    )),
    # --- sources / sinks
    AgentDoc("webcrawler-source", "Crawl websites into records", (
        _P("seed-urls", "list", "starting URLs", required=True),
        _P("allowed-domains", "list", "crawl boundary"),
        _P("forbidden-paths", "list", "paths to skip"),
        _P("max-urls", "integer", "crawl budget", default=1000),
        _P("max-depth", "integer", "link depth budget", default=50),
        _P("min-time-between-requests", "integer", "politeness delay ms", default=500),
        _P("reindex-interval-seconds", "integer", "recrawl period", default=86400),
        _P("user-agent", "string", "crawler user agent"),
        _P("handle-robots-file", "boolean", "honor robots.txt", default=True),
        _P("state-storage", "string", "checkpoint backend", choices=("disk", "s3")),
        _P("bucketName", "string", "s3 bucket for state"),
        _P("endpoint", "string", "s3 endpoint for state"),
        _P("access-key", "string", "s3 access key"),
        _P("secret-key", "string", "s3 secret key"),
        _P("region", "string", "s3 region"),
    ), category="source", allow_unknown=True),
    AgentDoc("s3-source", "Read objects from an S3 bucket", (
        _P("bucketName", "string", "bucket to read", default="langstream-source"),
        _P("endpoint", "string", "s3 endpoint"),
        _P("access-key", "string", "access key"),
        _P("secret-key", "string", "secret key"),
        _P("region", "string", "region"),
        _P("file-extensions", "string", "comma-separated extension filter"),
        _P("idle-time", "integer", "poll period seconds", default=5),
        _P("delete-objects", "boolean", "delete after processing", default=True),
    ), category="source"),
    AgentDoc("azure-blob-storage-source", "Read blobs from Azure storage", (
        _P("container", "string", "container name", default="langstream-azure-source"),
        _P("endpoint", "string", "storage endpoint (or derive from account name)"),
        _P("sas-token", "string", "SAS token"),
        _P("storage-account-name", "string", "account name"),
        _P("storage-account-key", "string", "account key"),
        _P("storage-account-connection-string", "string", "connection string"),
        _P("file-extensions", "string", "extension filter"),
        _P("idle-time", "integer", "poll period seconds", default=5),
        _P("delete-objects", "boolean", "delete after processing", default=True),
    ), category="source"),
    AgentDoc("file-source", "Read files from a local directory", (
        _P("path", "string", "directory to read", required=True),
        _P("file-extensions", "string", "extension filter"),
        _P("idle-time", "integer", "poll period seconds", default=5),
        _P("delete-objects", "boolean", "delete after processing", default=False),
    ), category="source"),
    AgentDoc("vector-db-sink", "Write embeddings/documents to a vector store", (
        _P("datasource", "string", "vector database resource", required=True),
    ), category="sink", allow_unknown=True),
    AgentDoc("query-vector-db", "Query a vector store into a field", (
        _P("datasource", "string", "vector database resource", required=True),
        _P("query", "string", "query with ? placeholders", required=True),
        _P("fields", "list", "expressions bound to placeholders"),
        _P("output-field", "string", "result field", required=True),
        _P("only-first", "boolean", "unwrap single result", default=False),
        _WHEN,
    )),
    AgentDoc("re-rank", "Re-rank retrieved documents (MMR)", (
        _P("field", "string", "field holding candidates", default="value.query-result"),
        _P("output-field", "string", "ranked output field (defaults to field)"),
        _P("query-embeddings", "string", "query vector expression",
           default="value.question_embeddings"),
        _P("vector-field", "string", "candidate vector key", default="vector"),
        _P("algorithm", "string", "ranking algorithm", default="MMR", choices=("MMR", "none")),
        _P("lambda", "number", "MMR relevance/diversity balance", default=0.5),
        _P("max", "integer", "results to keep", default=10),
    )),
    AgentDoc("http-request", "Call an HTTP endpoint per record", (
        _P("url", "string", "target URL template", required=True),
        _P("output-field", "string", "response field", default="value"),
        _P("method", "string", "HTTP method", default="GET"),
        _P("headers", "object", "request headers"),
        _P("query-string", "object", "query params (templated)"),
        _P("body", "string", "request body template"),
        _P("allow-redirects", "boolean", "follow redirects", default=True),
        _P("handle-cookies", "boolean", "keep a cookie jar", default=True),
    )),
    AgentDoc("python-source", "User Python source", (
        _P("className", "string", "python class path", required=True),
        _P("isolation", "string", "auto (process when the app ships python/lib deps, else in-process), none, or process (crash-isolated child)", default="auto"),
    ), category="source", allow_unknown=True),
    AgentDoc("python-processor", "User Python processor", (
        _P("className", "string", "python class path", required=True),
        _P("isolation", "string", "auto (process when the app ships python/lib deps, else in-process), none, or process (crash-isolated child)", default="auto"),
    ), allow_unknown=True),
    AgentDoc("python-sink", "User Python sink", (
        _P("className", "string", "python class path", required=True),
        _P("isolation", "string", "auto (process when the app ships python/lib deps, else in-process), none, or process (crash-isolated child)", default="auto"),
    ), category="sink", allow_unknown=True),
    AgentDoc("python-service", "User Python service", (
        _P("className", "string", "python class path", required=True),
        _P("isolation", "string", "auto (process when the app ships python/lib deps, else in-process), none, or process (crash-isolated child)", default="auto"),
    ), category="service", allow_unknown=True),
    AgentDoc("flare-controller", "FLARE iterative-retrieval loop controller", (
        _P("tokens-field", "string", "field with completion tokens", default="value.tokens"),
        _P("logprobs-field", "string", "field with token logprobs", default="value.logprobs"),
        _P("loop-topic", "string", "topic to send low-confidence records to", required=True),
        _P("retrieve-documents-field", "string", "field receiving the spans",
           default="value.documents_to_retrieve"),
        _P("min-prob", "number", "low-confidence probability threshold", default=0.2),
        _P("min-token-gap", "integer", "span merge distance", default=5),
        _P("num-pad-tokens", "integer", "span padding", default=2),
        _P("max-iterations", "integer", "loop bound", default=10),
        _P("num-iterations-field", "string", "iteration counter field",
           default="value.flare_iterations"),
    )),
    AgentDoc("langserve-invoke", "Call a LangServe runnable (invoke or stream)", (
        _P("url", "string", "LangServe endpoint (/invoke or /stream)", required=True),
        _P("fields", "list", "input fields: {name, expression}"),
        _P("output-field", "string", "final output field", default="value"),
        _P("content-field", "string", "chunk content field", default="value"),
        _P("stream-to-topic", "string", "topic for streamed chunks"),
        _P("min-chunks-per-message", "integer", "chunk batching ramp", default=20),
        _P("headers", "object", "extra HTTP headers"),
    )),
    AgentDoc("camel-source", "Consume a Camel endpoint URI (native "
             "timer/file/http(s)/kafka/netty-http/aws2-s3/"
             "azure-storage-blob/pulsar mappings; plugin schemes or "
             "exec-source for the rest)", (
        _P("component-uri", "string",
           "Camel endpoint, e.g. timer:tick?period=1000", required=True),
        _P("component-options", "object", "extra endpoint parameters"),
        _P("key-header", "string", "header whose value becomes the key"),
        _P("max-buffered-records", "integer", "read batch cap", default=100),
        _P("expect-plugin-scheme", "boolean",
           "defer unknown-scheme validation to runtime (a plugin "
           "package registers the scheme when the pod loads)",
           default=False),
    ), category="source"),
    AgentDoc("exec-source", "Run a command; stdout lines become records", (
        _P("command", "string", "command line to run", required=True),
        _P("parse-json", "boolean", "JSON-decode each line", default=True),
        _P("restart-seconds", "number", "restart backoff", default=5),
        _P("max-restarts", "integer", "0 = restart forever", default=0),
    ), category="source"),
    AgentDoc("exec-sink", "Run a command; records stream to its stdin", (
        _P("command", "string", "command line to run", required=True),
    ), category="sink"),
    AgentDoc("kafka-connect-source", "Run a Kafka Connect source connector", (
        _P("connect-url", "string", "Connect worker REST URL", required=True),
        _P("connector-name", "string", "connector name", required=True),
        _P("connector-config", "object", "raw Connect connector config",
           required=True),
        _P("topic", "string", "Kafka topic the connector writes",
           required=True),
        _P("bootstrapServers", "string", "Kafka bootstrap for the data topic"),
        _P("delete-on-close", "boolean", "delete the connector on shutdown",
           default=False),
        _P("rebalance-timeout", "number",
           "seconds to retry 409s while the worker group rebalances",
           default=30),
        _P("restart-failed-tasks", "boolean",
           "auto-restart FAILED connector tasks via the REST API",
           default=True),
        _P("health-check-interval", "number",
           "seconds between connector status polls (0 disables)",
           default=30),
    ), category="source"),
    AgentDoc("kafka-connect-sink", "Run a Kafka Connect sink connector", (
        _P("connect-url", "string", "Connect worker REST URL", required=True),
        _P("connector-name", "string", "connector name", required=True),
        _P("connector-config", "object", "raw Connect connector config",
           required=True),
        _P("topic", "string", "staging Kafka topic the connector consumes",
           required=True),
        _P("bootstrapServers", "string", "Kafka bootstrap for the data topic"),
        _P("delete-on-close", "boolean", "delete the connector on shutdown",
           default=False),
        _P("rebalance-timeout", "number",
           "seconds to retry 409s while the worker group rebalances",
           default=30),
        _P("restart-failed-tasks", "boolean",
           "auto-restart FAILED connector tasks via the REST API",
           default=True),
        _P("health-check-interval", "number",
           "seconds between connector status polls (0 disables)",
           default=30),
    ), category="sink"),
    AgentDoc("identity", "Pass records through unchanged", ()),
    AgentDoc("ai-tools", "GenAI toolkit executor (compiled steps)", (),
             allow_unknown=True),
    AgentDoc("composite-agent", "Fused pipeline of agents in one pod", (),
             allow_unknown=True),
]:
    register_doc(doc)
