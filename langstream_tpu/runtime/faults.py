"""Deterministic fault injection — chaos you can replay on CPU.

DeepServe (PAPERS.md, arxiv 2501.14417) treats failure detection and
instance recovery as first-class serving properties; to TEST that arc
(``runtime/supervisor.py``) the failures themselves must be first-class:
named fault points threaded through the engine device thread, the
dispatch builders, the paged allocator, and the mirror follower, armed
by a compact spec so the same crash replays bit-for-bit in CI and in a
``bench.py --chaos`` leg.

Spec grammar (comma-separated, via :func:`configure` or the
``LANGSTREAM_FAULTS`` env var)::

    LANGSTREAM_FAULTS="engine_thread_crash@step=40,dispatch_error@step=7:1.0"
    LANGSTREAM_FAULTS="stuck_step@step=5;dur=45,pool_exhausted@step=3"

    SPEC  := point '@' 'step=' N [':' PROB] (';' KEY '=' VALUE)*

- ``point@step=N``      — fire exactly on the Nth arrival at the point
  (one-shot: a supervisor-rebuilt engine passing the same point again
  does NOT re-fire, because arrival counters are process-global).
- ``point@step=N:P``    — armed from the Nth arrival on; each arrival
  fires with probability P, derived deterministically from
  ``sha256(point, arrival, seed)`` (seed: ``LANGSTREAM_FAULTS_SEED``),
  so a given spec+seed produces the identical fault sequence every run.
- ``;key=value`` params — handler-specific knobs (e.g. ``stuck_step``'s
  ``dur`` sleep seconds).

Fault points wired today (the registry itself is generic — call sites
decide what firing means):

=====================  ==================================================
``engine_thread_crash``  engine device thread dies after the Nth decode
                         chunk is fully emitted (raises
                         :class:`InjectedFault` in the engine loop)
``stuck_step``           engine loop sleeps ``dur`` seconds (default 30)
                         — a wedged dispatch for watchdog/escalation
                         tests without real stalls
``dispatch_error``       a prefill/decode dispatch builder raises
                         :class:`InjectedFault` before dispatching
``pool_exhausted``       the paged block allocator reports an exhausted
                         pool (``allocate`` returns None) — admission
                         backpressure on demand
``mirror_follower``      the multi-host follower executor raises while
                         replaying the leader's dispatch stream
=====================  ==================================================

Unarmed (the default) every check is one attribute read — chaos costs
nothing in production. Every firing leaves a ``fault_injected`` flight
record so recovery evidence names its cause.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "LANGSTREAM_FAULTS"
SEED_ENV_VAR = "LANGSTREAM_FAULTS_SEED"


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised unarmed)."""

    def __init__(self, point: str, arrival: int) -> None:
        super().__init__(f"injected fault {point!r} (arrival {arrival})")
        self.point = point
        self.arrival = arrival


class FaultSpec:
    """One armed fault: point name, trigger step, probability, params."""

    __slots__ = ("point", "step", "prob", "params", "fired")

    def __init__(
        self,
        point: str,
        step: int,
        prob: Optional[float] = None,
        params: Optional[Dict[str, str]] = None,
    ) -> None:
        self.point = point
        self.step = max(1, int(step))
        self.prob = prob  # None = one-shot exactly at `step`
        self.params = params or {}
        self.fired = 0

    def should_fire(self, arrival: int, seed: int) -> bool:
        if self.prob is None:
            return arrival == self.step
        if arrival < self.step or self.prob <= 0.0:
            return False
        if self.prob >= 1.0:
            return True
        # deterministic per-(point, arrival, seed) coin: replaying the
        # same spec reproduces the identical fault sequence
        digest = hashlib.sha256(
            f"{self.point}:{arrival}:{seed}".encode()
        ).digest()
        draw = int.from_bytes(digest[:8], "big") / float(1 << 64)
        return draw < self.prob

    def describe(self) -> str:
        spec = f"{self.point}@step={self.step}"
        if self.prob is not None:
            spec += f":{self.prob}"
        for key, value in sorted(self.params.items()):
            spec += f";{key}={value}"
        return spec


def parse_spec(text: str) -> List[FaultSpec]:
    """Parse a comma-separated fault spec string (see module docstring).
    Raises ValueError on malformed entries — a typo'd chaos spec must
    fail the run loudly, not silently test nothing."""
    out: List[FaultSpec] = []
    for entry in (text or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        point, at, rest = entry.partition("@")
        if not at or not point:
            raise ValueError(f"fault spec {entry!r}: expected point@step=N")
        parts = rest.split(";")
        head = parts[0]
        if not head.startswith("step="):
            raise ValueError(f"fault spec {entry!r}: expected step=N")
        step_text, colon, prob_text = head[len("step="):].partition(":")
        try:
            step = int(step_text)
            prob = float(prob_text) if colon else None
        except ValueError:
            raise ValueError(
                f"fault spec {entry!r}: bad step/probability"
            ) from None
        if prob is not None and not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault spec {entry!r}: probability not in [0,1]")
        params: Dict[str, str] = {}
        for param in parts[1:]:
            key, eq, value = param.partition("=")
            if not eq or not key:
                raise ValueError(f"fault spec {entry!r}: bad param {param!r}")
            params[key.strip()] = value.strip()
        out.append(FaultSpec(point.strip(), step, prob, params))
    return out


class FaultRegistry:
    """Process-global fault points. ``fire()`` counts an arrival and
    returns the triggering :class:`FaultSpec` (or None); ``check()``
    additionally raises :class:`InjectedFault`. Arrival counters are
    monotonic per point for the process lifetime, so a one-shot fault
    consumed by a crashed engine stays consumed across its supervisor
    rebuild."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._arrivals: Dict[str, int] = {}
        self._seed = 0
        self.armed = False  # fast path: one attribute read when off

    def configure(self, text: str, seed: Optional[int] = None) -> None:
        """Arm the registry from a spec string (replaces any previous
        arming; empty string disarms)."""
        specs = parse_spec(text)
        with self._lock:
            self._specs = {}
            for spec in specs:
                self._specs.setdefault(spec.point, []).append(spec)
            if seed is not None:
                self._seed = int(seed)
            self.armed = bool(self._specs)
        if self.armed:
            logger.warning(
                "fault injection ARMED: %s",
                ",".join(s.describe() for s in specs),
            )

    def configure_from_env(self) -> None:
        text = os.environ.get(ENV_VAR, "")
        if text:
            self.configure(
                text, seed=int(os.environ.get(SEED_ENV_VAR, "0") or "0")
            )

    def reset(self) -> None:
        """Disarm and zero every arrival counter (tests)."""
        with self._lock:
            self._specs = {}
            self._arrivals = {}
            self._seed = 0
            self.armed = False

    def describe(self) -> str:
        with self._lock:
            return ",".join(
                spec.describe()
                for specs in self._specs.values()
                for spec in specs
            )

    def fire(self, point: str) -> Optional[FaultSpec]:
        """Count an arrival at ``point``; return the spec that fires (if
        any). The unarmed fast path never takes the lock."""
        if not self.armed:
            return None
        with self._lock:
            arrival = self._arrivals.get(point, 0) + 1
            self._arrivals[point] = arrival
            specs = self._specs.get(point)
            if not specs:
                return None
            for spec in specs:
                if spec.should_fire(arrival, self._seed):
                    spec.fired += 1
                    self._record(spec, arrival)
                    return spec
        return None

    def check(self, point: str) -> None:
        """Arrival + raise :class:`InjectedFault` when a spec fires."""
        if not self.armed:
            return
        spec = self.fire(point)
        if spec is not None:
            raise InjectedFault(point, self._arrivals[point])

    def maybe_sleep(self, point: str, default_s: float = 30.0) -> float:
        """Arrival + sleep when a spec fires (the ``stuck_step`` shape:
        a dispatch that wedges instead of erroring). Returns the slept
        seconds (0.0 = did not fire)."""
        if not self.armed:
            return 0.0
        spec = self.fire(point)
        if spec is None:
            return 0.0
        duration = float(spec.params.get("dur", default_s))
        time.sleep(duration)
        return duration

    def _record(self, spec: FaultSpec, arrival: int) -> None:
        # evidence trail: a chaos run's flight artifact names every
        # injected failure, so ab_analyze / a human reading a recovery
        # never has to guess whether a crash was organic
        logger.warning(
            "fault injection FIRING: %s (arrival %d)",
            spec.describe(), arrival,
        )
        from langstream_tpu.runtime import flight

        flight.record(
            "fault_injected",
            point=spec.point,
            arrival=arrival,
            spec=spec.describe(),
        )
        flight.flush()


REGISTRY = FaultRegistry()


def configure(text: str, seed: Optional[int] = None) -> None:
    REGISTRY.configure(text, seed=seed)


def configure_from_env() -> None:
    REGISTRY.configure_from_env()


def reset() -> None:
    REGISTRY.reset()


def armed() -> bool:
    return REGISTRY.armed


def fire(point: str) -> Optional[FaultSpec]:
    return REGISTRY.fire(point)


def check(point: str) -> None:
    REGISTRY.check(point)


def maybe_sleep(point: str, default_s: float = 30.0) -> float:
    return REGISTRY.maybe_sleep(point, default_s)
