"""Engine flight recorder — crash-surviving perf evidence on disk.

A bounded in-memory ring of telemetry samples (decode step latency, slot
occupancy, admission-queue depth, KV-cache pressure, phase marks...)
flushed periodically and at shutdown/crash to a JSONL artifact, so every
``serve``/bench run — including one that dies at backend-init — leaves
on-disk evidence the scoreboard and ``tools/ab_analyze.py`` can consume
(VERDICT r5: a bench session dying at backend-init left nothing behind).

One artifact per process under ``<dir>/flight_<utc>_<pid>.jsonl``; each
line is ``{"ts": <epoch s>, "kind": <sample kind>, ...fields}``. The
first line is a ``meta`` record identifying the process. Kinds written
by the current emitters:

- ``phase``         — coarse lifecycle marks (bench phases, serve boot)
- ``engine_start``  — engine built: slots, ctx, mesh
- ``prefill``       — one prefill dispatch: bucket, batch, warm, wall_ms
- ``decode_chunk``  — one decode dispatch: steps, active, slots,
  step_ms, queue_depth, kv_frac, tokens (cumulative)
- ``engine_crash``  — the engine loop died: error repr
- ``engine_stop``   — clean engine shutdown + final stats
- ``fault_injected``    — a deterministic chaos fault fired
  (``runtime/faults.py``): point, arrival, spec
- ``engine_recovery``   — supervisor heal arc (``runtime/supervisor.py``):
  phase begin/complete/gave_up/rebuild_failed, sessions, recovery_s
- ``session_resume``    — one resurrected session fast-forwarded:
  slot, replayed tokens, prefix-cache-reused tokens
- ``request_shed``      — a pending request failed fast at its
  admission deadline: waited_s, queue_depth, retry_after_s
- ``watchdog_escalation`` — N watchdog trips in a window handed the
  engine to the supervisor
- ``kv_handoff_export`` / ``kv_handoff_import`` — disaggregation KV
  chain serialized to / imported from the topic fabric
- ``journey``         — one finished (or handed-off) request leg's
  stage events (``runtime/journey.py``): trace_id, admit_class, and
  ``stages`` tiling the leg's wall clock — joined fleet-wide by
  ``langstream-tpu journey``

The ``meta`` record additionally carries ``replica`` + ``fleet_role``
when :func:`set_identity` has stamped the process's fleet identity
(serve threads ``--fleet-replica-id`` / ``--fleet-role``; bench stamps
a synthetic id), so artifact consumers can label samples per pod.

Disabled (the default) the recorder is a single ``if`` per call; enable
with :func:`configure` or the ``LANGSTREAM_FLIGHT_DIR`` env var (every
DecodeEngine construction calls :func:`configure_from_env`).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

ENV_VAR = "LANGSTREAM_FLIGHT_DIR"


class FlightRecorder:
    def __init__(
        self, capacity: int = 8192, flush_interval: float = 5.0
    ) -> None:
        self.capacity = capacity
        self.flush_interval = flush_interval
        # armed-path latch: written once under the lock by configure();
        # record()'s lock-free read is the zero-cost disabled gate (a
        # racing enable loses at most the samples of that instant)
        self.path: Optional[str] = None  # guarded-by: _lock (writes)
        self.dropped = 0  # guarded-by: _lock
        # fleet identity (replica id + role), stamped into the meta
        # record so the journey ledger can tell pods apart when it
        # joins fleet-wide artifacts by trace id
        self.identity: Dict[str, str] = {}  # guarded-by: _lock
        self._pending: Deque[Dict[str, Any]] = deque(maxlen=capacity)  # guarded-by: _lock
        self._lock = threading.Lock()
        self._last_flush = 0.0  # guarded-by: _lock
        self._atexit_registered = False  # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def configure(
        self, directory: str, run_id: Optional[str] = None
    ) -> str:
        """Open (or re-target) the artifact file; idempotent per dir.
        Returns the artifact path. Writes the ``meta`` line immediately
        so even a process that dies before any sample leaves a file."""
        with self._lock:
            if self.path is not None and os.path.dirname(self.path) == directory:
                return self.path
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            name = f"flight_{stamp}_{os.getpid()}.jsonl"
            self.path = os.path.join(directory, name)
            if not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self.flush)
            identity = dict(self.identity)
        self.record(
            "meta",
            pid=os.getpid(),
            run_id=run_id or "",
            argv=" ".join(sys.argv[:4]),
            **identity,
        )
        self.flush()
        return self.path

    def set_identity(
        self, replica: Optional[str], fleet_role: Optional[str] = None
    ) -> None:
        """Stamp the fleet identity of this process. Called before
        :meth:`configure`, it rides the artifact's first ``meta`` line;
        called after (serve learns its ``--fleet-replica-id`` past
        backend init), a supplementary ``meta`` record carries it —
        :func:`read_artifact` consumers take the last value seen."""
        with self._lock:
            if replica:
                self.identity["replica"] = str(replica)
            if fleet_role:
                self.identity["fleet_role"] = str(fleet_role)
            identity = dict(self.identity)
            enabled = self.path is not None
        if enabled and identity:
            self.record("meta", pid=os.getpid(), **identity)

    def record(self, kind: str, **fields: Any) -> None:
        """Append one sample; cheap no-op when disabled. Auto-flushes
        when ``flush_interval`` has elapsed since the last write, so a
        hard kill loses at most one interval of samples."""
        if self.path is None:
            return
        entry = {"ts": round(time.time(), 6), "kind": kind}
        entry.update(fields)
        with self._lock:
            if len(self._pending) == self._pending.maxlen:
                self.dropped += 1
            self._pending.append(entry)
            due = time.monotonic() - self._last_flush >= self.flush_interval
        if due:
            self.flush()

    def flush(self) -> None:
        """Drain the ring to the artifact (append-only JSONL)."""
        with self._lock:
            if self.path is None or not self._pending:
                return
            batch: List[Dict[str, Any]] = list(self._pending)
            self._pending.clear()
            self._last_flush = time.monotonic()
            path = self.path
            if self.dropped:
                batch.insert(
                    0,
                    {
                        "ts": round(time.time(), 6),
                        "kind": "dropped",
                        "count": self.dropped,
                    },
                )
                self.dropped = 0
        try:
            with open(path, "a", encoding="utf-8") as handle:
                for entry in batch:
                    handle.write(json.dumps(entry) + "\n")
        except OSError:
            # a full/readonly disk must never take down the data plane
            pass


RECORDER = FlightRecorder()


def configure(directory: str, run_id: Optional[str] = None) -> str:
    return RECORDER.configure(directory, run_id=run_id)


def configure_from_env() -> Optional[str]:
    directory = os.environ.get(ENV_VAR, "")
    if directory and not RECORDER.enabled:
        return RECORDER.configure(directory)
    return RECORDER.path


def record(kind: str, **fields: Any) -> None:
    RECORDER.record(kind, **fields)


def set_identity(
    replica: Optional[str], fleet_role: Optional[str] = None
) -> None:
    RECORDER.set_identity(replica, fleet_role)


def get_identity() -> Dict[str, str]:
    with RECORDER._lock:
        return dict(RECORDER.identity)


def flush() -> None:
    RECORDER.flush()


def read_artifact(path: str) -> List[Dict[str, Any]]:
    """Parse a flight JSONL artifact, skipping any torn final line (the
    process may have died mid-write — that is the artifact's whole
    reason to exist)."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as handle:
        for line in handle:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def latest_artifact(directory: str) -> Optional[str]:
    try:
        names = [
            n for n in os.listdir(directory)
            if n.startswith("flight_") and n.endswith(".jsonl")
        ]
    except OSError:
        return None
    if not names:
        return None
    names.sort()
    return os.path.join(directory, names[-1])
