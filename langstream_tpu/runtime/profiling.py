"""On-demand device profiler capture (one at a time per process).

Backs the ``/debug/profile?seconds=N`` endpoint on runner pods and the
OpenAI server, the ``langstream-tpu profile`` CLI verb, and the
watchdog's automatic evidence capture. Each capture runs
``jax.profiler.trace`` for N seconds (everything the devices execute in
the window lands in the xplane trace — MXU utilization, HBM stalls,
fusion names) plus a per-device memory snapshot, into
``bench_artifacts/profiles/<utc>_<pid>/``.

A single in-flight capture is enforced process-wide: the profiler is a
global singleton in JAX, and overlapping traces corrupt each other. A
second concurrent request raises :class:`ProfileBusyError` (HTTP 409 on
the serving surfaces).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_DIR = "LANGSTREAM_PROFILE_DIR"
MAX_SECONDS = 60.0


class ProfileBusyError(RuntimeError):
    """A profiler capture is already in progress in this process."""


_ACTIVE = threading.Lock()


def busy() -> bool:
    return _ACTIVE.locked()


def default_dir() -> str:
    """``$LANGSTREAM_PROFILE_DIR``, else ``bench_artifacts/profiles``
    next to the repo's other artifacts when running from a checkout
    (where ``tools/ab_analyze.py`` and the flight recorder live),
    CWD-relative otherwise."""
    env = os.environ.get(ENV_DIR)
    if env:
        return env
    import langstream_tpu

    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(langstream_tpu.__file__))
    )
    base = (
        os.path.join(repo_root, "bench_artifacts")
        if os.path.isdir(os.path.join(repo_root, "bench_artifacts"))
        else "bench_artifacts"
    )
    return os.path.join(base, "profiles")


def device_memory_snapshot() -> List[Dict[str, Any]]:
    """Per-device memory stats (bytes in use / peak / limit where the
    backend reports them). Tolerates backends without ``memory_stats``
    (CPU) — the snapshot still records the device inventory."""
    import jax

    out: List[Dict[str, Any]] = []
    for device in jax.devices():
        stats: Dict[str, Any] = {}
        try:
            stats = dict(device.memory_stats() or {})
        except Exception:  # noqa: BLE001 — not all backends implement it
            pass
        out.append({
            "id": device.id,
            "platform": device.platform,
            "kind": getattr(device, "device_kind", ""),
            "memory_stats": stats,
        })
    return out


def capture(seconds: float, base_dir: Optional[str] = None) -> str:
    """Run one profiler capture; returns the artifact directory.

    Raises :class:`ProfileBusyError` when a capture is already running,
    ``ValueError`` on an out-of-range duration. The caller's device work
    continues normally during the window — the trace records it."""
    seconds = float(seconds)
    if not 0 < seconds <= MAX_SECONDS:
        raise ValueError(
            f"seconds must be in (0, {MAX_SECONDS:.0f}], got {seconds}"
        )
    if not _ACTIVE.acquire(blocking=False):
        raise ProfileBusyError(
            "a profiler capture is already in progress (one at a time)"
        )
    try:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        directory = os.path.join(
            base_dir or default_dir(), f"{stamp}_{os.getpid()}"
        )
        os.makedirs(directory, exist_ok=True)
        import jax

        started = time.perf_counter()
        with jax.profiler.trace(directory):
            time.sleep(seconds)
        snapshot = {
            "captured_s": round(time.perf_counter() - started, 3),
            "devices": device_memory_snapshot(),
        }
        with open(
            os.path.join(directory, "device_memory.json"), "w",
            encoding="utf-8",
        ) as handle:
            json.dump(snapshot, handle, indent=2)
        logger.info("profiler capture (%.1fs) -> %s", seconds, directory)
        return directory
    finally:
        _ACTIVE.release()
