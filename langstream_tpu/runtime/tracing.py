"""Step-level tracing + on-demand TPU profiling.

The reference has NO tracing subsystem (SURVEY §5: "Tracing / profiling:
ABSENT" — observability there is Prometheus counters + a periodic stats
dump, AgentRunner.java:598-618). This is a net-new subsystem of the TPU
build, in two layers:

1. **Span tracing** (any platform): lightweight in-process spans with
   wall-time + monotonic durations, parent links, and per-record
   attributes, kept in a bounded ring buffer per :class:`Tracer` and
   exportable as Chrome ``trace_event`` JSON (load in
   ``chrome://tracing`` / Perfetto). The runner wraps each hot-loop
   phase (read / process / write / commit) in spans when given a tracer.

2. **XLA device profiling** (TPU/CPU): :func:`profile` wraps
   ``jax.profiler.trace`` to capture an xplane trace of everything the
   devices ran — the tool for MXU utilization and HBM stalls. Written
   to a TensorBoard-compatible directory.

Overhead when disabled: a single ``if`` per call site (module-level
no-op tracer).
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import glob
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional, Sequence

# the one wire-level trace-context contract: the gateway (or any other
# edge) stamps this record header on ingress; the runner re-attaches it
# on every emitted record so it survives topic hops; the engine tags its
# per-request spans with it. See docs/observability.md.
TRACE_ID_HEADER = "langstream-trace-id"


def new_trace_id() -> str:
    return uuid.uuid4().hex


def trace_dir() -> str:
    """Directory for per-process Chrome-trace dumps; empty = tracing off
    (``get_tracer`` then hands out the shared no-op tracer)."""
    return os.environ.get("LANGSTREAM_TRACE_DIR", "")


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_wall",
        "start_ns", "duration_ns", "attributes",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: int,
        parent_id: Optional[int],
        attributes: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_wall = time.time()
        self.start_ns = time.perf_counter_ns()
        self.duration_ns: Optional[int] = None
        self.attributes: Dict[str, Any] = attributes or {}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start_wall,
            "duration_ms": (
                None if self.duration_ns is None else self.duration_ns / 1e6
            ),
            "attributes": self.attributes,
        }


class Tracer:
    """Per-component span recorder with a bounded buffer."""

    def __init__(self, component: str, max_spans: int = 4096) -> None:
        self.component = component
        self.enabled = True
        self._spans: Deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._counter = 0
        # ContextVar, not threading.local: the runner opens spans around
        # awaits in concurrent asyncio tasks on ONE event-loop thread —
        # a thread-local "current span" would cross-link unrelated tasks
        self._current: "contextvars.ContextVar[Optional[Span]]" = (
            contextvars.ContextVar(f"span_{component}", default=None)
        )

    def _next_id(self) -> int:
        with self._lock:
            self._counter += 1
            return self._counter

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        trace_id: str = "",
        **attributes: Any,
    ) -> Iterator[Span]:
        """Record a span; nests under the current thread's open span."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        parent = self._current.get()
        span = Span(
            name=name,
            trace_id=trace_id or (parent.trace_id if parent else ""),
            span_id=self._next_id(),
            parent_id=parent.span_id if parent else None,
            attributes=attributes,
        )
        token = self._current.set(span)
        try:
            yield span
        finally:
            span.duration_ns = time.perf_counter_ns() - span.start_ns
            self._current.reset(token)
            with self._lock:
                self._spans.append(span)

    def event(
        self,
        name: str,
        duration_s: float,
        *,
        trace_id: str = "",
        start_wall: Optional[float] = None,
        **attributes: Any,
    ) -> None:
        """Record an already-completed span from measurements taken
        elsewhere (the engine thread times its phases itself — a
        contextmanager around multi-iteration device work would lie)."""
        if not self.enabled:
            return
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=self._next_id(),
            parent_id=None,
            attributes=attributes,
        )
        if start_wall is not None:
            span.start_wall = start_wall
        span.duration_ns = max(0, int(duration_s * 1e9))
        with self._lock:
            self._spans.append(span)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #
    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Chrome trace_event "X" (complete) events — open the JSON in
        chrome://tracing or Perfetto."""
        events = []
        with self._lock:
            spans = list(self._spans)
        for span in spans:
            if span.duration_ns is None:
                continue
            events.append({
                "name": span.name,
                "cat": self.component,
                "ph": "X",
                "ts": span.start_wall * 1e6,
                "dur": span.duration_ns / 1e3,
                "pid": 0,
                "tid": span.parent_id or span.span_id,
                "args": {"trace_id": span.trace_id, **span.attributes},
            })
        return events

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"traceEvents": self.chrome_trace()}, fh)


class _NoopSpan:
    __slots__ = ()

    @property
    def attributes(self) -> Dict[str, Any]:
        # fresh throwaway dict per access: callers may write into a live
        # span's attributes, and the shared no-op must absorb that
        # without accumulating state
        return {}

    def __setattr__(self, *_a) -> None:  # pragma: no cover
        pass


_NOOP_SPAN = _NoopSpan()


class NoopTracer(Tracer):
    """Shared do-nothing tracer (the default when tracing is off)."""

    def __init__(self) -> None:
        super().__init__("noop", max_spans=1)
        self.enabled = False


NOOP = NoopTracer()


# ---------------------------------------------------------------------- #
# process-wide tracer registry + auto-dump
# ---------------------------------------------------------------------- #
_TRACERS: Dict[str, Tracer] = {}
_REGISTRY_LOCK = threading.Lock()
_DUMP_REGISTERED = False


def get_tracer(component: str) -> Tracer:
    """The process-wide tracer for a component (``gateway``, ``runner``,
    ``engine``...). Returns :data:`NOOP` unless ``LANGSTREAM_TRACE_DIR``
    is set, so call sites pay one attribute check when tracing is off.
    Real tracers are dumped to the trace dir at interpreter exit (and on
    demand via :func:`dump_all`)."""
    global _DUMP_REGISTERED
    if not trace_dir():
        return NOOP
    with _REGISTRY_LOCK:
        tracer = _TRACERS.get(component)
        if tracer is None:
            tracer = Tracer(component)
            _TRACERS[component] = tracer
        if not _DUMP_REGISTERED:
            _DUMP_REGISTERED = True
            atexit.register(dump_all)
    return tracer


def dump_all(directory: Optional[str] = None) -> List[str]:
    """Write one Chrome-trace JSON per registered tracer into the trace
    dir; file names carry the component and pid so a multi-pod run's
    dumps never collide and ``trace_merge`` can label them."""
    directory = directory or trace_dir()
    if not directory:
        return []
    os.makedirs(directory, exist_ok=True)
    paths = []
    with _REGISTRY_LOCK:
        tracers = dict(_TRACERS)
    for component, tracer in tracers.items():
        events = tracer.chrome_trace()
        if not events:
            continue
        path = os.path.join(
            directory, f"trace_{component}_{os.getpid()}.json"
        )
        tracer.dump(path)
        paths.append(path)
    return paths


# ---------------------------------------------------------------------- #
# cross-pod trace merging (tools/trace_merge.py + `langstream-tpu trace`)
# ---------------------------------------------------------------------- #
def collect_trace_files(paths: Sequence[str]) -> List[str]:
    """Expand dirs into their ``*.json`` dumps; keep files as given."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(glob.glob(os.path.join(path, "*.json"))))
        else:
            out.append(path)
    return out


def _event_trace_ids(event: Dict[str, Any]) -> List[str]:
    args = event.get("args") or {}
    ids = []
    if args.get("trace_id"):
        ids.append(str(args["trace_id"]))
    # batch-level spans (decode chunks) carry every rider's id
    if args.get("trace_ids"):
        ids.extend(
            t for t in str(args["trace_ids"]).split(",") if t
        )
    return ids


def merge_chrome_trace_files(
    paths: Sequence[str], trace_id: Optional[str] = None
) -> Dict[str, Any]:
    """Merge per-pod Chrome-trace dumps into ONE Perfetto-loadable
    timeline: each source file becomes a distinct ``pid`` (named after
    the file via process_name metadata), events keep their wall-clock
    ``ts`` so cross-pod ordering is real time. With ``trace_id``, only
    events belonging to that request survive."""
    events: List[Dict[str, Any]] = []
    for pid, path in enumerate(collect_trace_files(paths), start=1):
        with open(path) as handle:
            data = json.load(handle)
        # both Chrome trace shapes: {"traceEvents": [...]} or bare array
        source = data.get("traceEvents", []) if isinstance(data, dict) else data
        label = os.path.splitext(os.path.basename(path))[0]
        kept = []
        for event in source:
            if trace_id is not None and trace_id not in _event_trace_ids(event):
                continue
            event = dict(event)
            event["pid"] = pid
            kept.append(event)
        if kept:
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "ts": 0, "args": {"name": label},
            })
            events.extend(kept)
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": events}


def run_trace_merge(
    paths: Sequence[str],
    *,
    output: str = "merged_trace.json",
    trace_id: Optional[str] = None,
    list_ids: bool = False,
) -> List[str]:
    """The one CLI body behind ``langstream-tpu trace`` AND
    ``tools/trace_merge.py``: expand paths, list ids or write the merged
    timeline, return the status lines to print."""
    files = collect_trace_files(paths)
    if not files:
        raise SystemExit(f"no trace dumps under {list(paths)}")
    if list_ids:
        summary = trace_summary(files)
        if not summary:
            return ["no trace ids found"]
        return [
            f"{tid}  components={','.join(entry['components'])}  "
            f"spans={entry['spans']}"
            + (
                f"  replicas={','.join(entry['replicas'])}"
                if entry.get("replicas") else ""
            )
            for tid, entry in sorted(summary.items())
        ]
    merged = merge_chrome_trace_files(files, trace_id=trace_id)
    with open(output, "w") as handle:
        json.dump(merged, handle)
    return [
        f"wrote {len(merged['traceEvents'])} events from {len(files)} "
        f"dump(s) -> {output} (open in Perfetto / chrome://tracing)"
    ]


def trace_summary(paths: Sequence[str]) -> Dict[str, Dict[str, Any]]:
    """Per-trace-id view over a set of dumps: which components a request
    crossed, how many spans each contributed, and — for spans stamped
    with a ``replica`` attr (gateway route decisions, engine handoff
    spans on identity-stamped serve processes) — which REPLICAS the
    request crossed, so a disaggregated prefill→decode path reads as
    two replicas under one id from ``langstream-tpu trace --list``."""
    out: Dict[str, Dict[str, Any]] = {}
    for path in collect_trace_files(paths):
        with open(path) as handle:
            data = json.load(handle)
        events = data.get("traceEvents", []) if isinstance(data, dict) else data
        for event in events:
            category = event.get("cat", "?")
            replica = (event.get("args") or {}).get("replica")
            for tid in _event_trace_ids(event):
                entry = out.setdefault(
                    tid,
                    {"components": set(), "spans": 0, "replicas": set()},
                )
                entry["components"].add(category)
                entry["spans"] += 1
                if replica:
                    entry["replicas"].add(str(replica))
    for entry in out.values():
        entry["components"] = sorted(entry["components"])
        entry["replicas"] = sorted(entry["replicas"])
    return out


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture an XLA device profile (xplane) under ``log_dir`` —
    TensorBoard's profile plugin or xprof reads it. Wraps
    ``jax.profiler.trace``; everything the devices execute inside the
    block is captured (MXU utilization, HBM traffic, fusion names)."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
