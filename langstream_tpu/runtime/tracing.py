"""Step-level tracing + on-demand TPU profiling.

The reference has NO tracing subsystem (SURVEY §5: "Tracing / profiling:
ABSENT" — observability there is Prometheus counters + a periodic stats
dump, AgentRunner.java:598-618). This is a net-new subsystem of the TPU
build, in two layers:

1. **Span tracing** (any platform): lightweight in-process spans with
   wall-time + monotonic durations, parent links, and per-record
   attributes, kept in a bounded ring buffer per :class:`Tracer` and
   exportable as Chrome ``trace_event`` JSON (load in
   ``chrome://tracing`` / Perfetto). The runner wraps each hot-loop
   phase (read / process / write / commit) in spans when given a tracer.

2. **XLA device profiling** (TPU/CPU): :func:`profile` wraps
   ``jax.profiler.trace`` to capture an xplane trace of everything the
   devices ran — the tool for MXU utilization and HBM stalls. Written
   to a TensorBoard-compatible directory.

Overhead when disabled: a single ``if`` per call site (module-level
no-op tracer).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_wall",
        "start_ns", "duration_ns", "attributes",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: int,
        parent_id: Optional[int],
        attributes: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_wall = time.time()
        self.start_ns = time.perf_counter_ns()
        self.duration_ns: Optional[int] = None
        self.attributes: Dict[str, Any] = attributes or {}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start_wall,
            "duration_ms": (
                None if self.duration_ns is None else self.duration_ns / 1e6
            ),
            "attributes": self.attributes,
        }


class Tracer:
    """Per-component span recorder with a bounded buffer."""

    def __init__(self, component: str, max_spans: int = 4096) -> None:
        self.component = component
        self.enabled = True
        self._spans: Deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._counter = 0
        # ContextVar, not threading.local: the runner opens spans around
        # awaits in concurrent asyncio tasks on ONE event-loop thread —
        # a thread-local "current span" would cross-link unrelated tasks
        self._current: "contextvars.ContextVar[Optional[Span]]" = (
            contextvars.ContextVar(f"span_{component}", default=None)
        )

    def _next_id(self) -> int:
        with self._lock:
            self._counter += 1
            return self._counter

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        trace_id: str = "",
        **attributes: Any,
    ) -> Iterator[Span]:
        """Record a span; nests under the current thread's open span."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        parent = self._current.get()
        span = Span(
            name=name,
            trace_id=trace_id or (parent.trace_id if parent else ""),
            span_id=self._next_id(),
            parent_id=parent.span_id if parent else None,
            attributes=attributes,
        )
        token = self._current.set(span)
        try:
            yield span
        finally:
            span.duration_ns = time.perf_counter_ns() - span.start_ns
            self._current.reset(token)
            with self._lock:
                self._spans.append(span)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #
    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Chrome trace_event "X" (complete) events — open the JSON in
        chrome://tracing or Perfetto."""
        events = []
        with self._lock:
            spans = list(self._spans)
        for span in spans:
            if span.duration_ns is None:
                continue
            events.append({
                "name": span.name,
                "cat": self.component,
                "ph": "X",
                "ts": span.start_wall * 1e6,
                "dur": span.duration_ns / 1e3,
                "pid": 0,
                "tid": span.parent_id or span.span_id,
                "args": {"trace_id": span.trace_id, **span.attributes},
            })
        return events

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"traceEvents": self.chrome_trace()}, fh)


class _NoopSpan:
    __slots__ = ()
    attributes: Dict[str, Any] = {}

    def __setattr__(self, *_a) -> None:  # pragma: no cover
        pass


_NOOP_SPAN = _NoopSpan()


class NoopTracer(Tracer):
    """Shared do-nothing tracer (the default when tracing is off)."""

    def __init__(self) -> None:
        super().__init__("noop", max_spans=1)
        self.enabled = False


NOOP = NoopTracer()


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture an XLA device profile (xplane) under ``log_dir`` —
    TensorBoard's profile plugin or xprof reads it. Wraps
    ``jax.profiler.trace``; everything the devices execute inside the
    block is captured (MXU utilization, HBM traffic, fusion names)."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
