"""Micro-batching executors.

Equivalents of the reference's batching utilities
(``langstream-api/src/main/java/ai/langstream/api/util/BatchExecutor.java:30``
and ``OrderedAsyncBatchExecutor.java:39``), asyncio-native. These are the
seam where streaming per-record semantics meet XLA's batch world: the
embeddings step and the completions engine use them to coalesce records into
one padded device call while preserving per-key ordering.

Design notes vs the reference:

- ``BatchExecutor``: flush on size OR linger timeout, like the reference
  (size+time flush, ``BatchExecutor.java:30``). Optionally also flushes on a
  byte budget — useful for bucketed-padding XLA calls where token count, not
  record count, bounds the batch.
- ``OrderedAsyncBatchExecutor``: N hash buckets; per bucket at most one
  in-flight async batch, so records that share a key are processed in order
  even though completion is async (``OrderedAsyncBatchExecutor.java:41-97``).
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")

AsyncBatchProcessor = Callable[[List[T]], Awaitable[None]]


class BatchExecutor(Generic[T]):
    """Flush a growing batch on size, byte budget, or linger timeout."""

    def __init__(
        self,
        batch_size: int,
        processor: AsyncBatchProcessor,
        *,
        flush_interval: float = 0.0,
        max_bytes: int = 0,
        size_of: Optional[Callable[[T], int]] = None,
    ) -> None:
        if batch_size <= 0:
            raise ValueError("batch_size must be > 0")
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.max_bytes = max_bytes
        self.size_of = size_of
        self.processor = processor
        self._batch: List[T] = []
        self._bytes = 0
        self._timer: Optional[asyncio.TimerHandle] = None

    async def add(self, item: T) -> None:
        self._batch.append(item)
        if self.max_bytes and self.size_of is not None:
            self._bytes += self.size_of(item)
        if len(self._batch) >= self.batch_size or (
            self.max_bytes and self._bytes >= self.max_bytes
        ):
            await self.flush()
        elif self.flush_interval > 0 and self._timer is None:
            loop = asyncio.get_running_loop()
            self._timer = loop.call_later(
                self.flush_interval,
                lambda: asyncio.ensure_future(self.flush()),
            )

    async def flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._batch:
            return
        batch, self._batch, self._bytes = self._batch, [], 0
        await self.processor(batch)

    async def close(self) -> None:
        await self.flush()


class OrderedAsyncBatchExecutor(Generic[T]):
    """N hash buckets, each preserving submission order with async batches.

    A record is routed to ``hash_fn(item) % buckets`` (records without a key
    hash to a rotating bucket). Within a bucket, batch *k+1* is not started
    until batch *k*'s processor coroutine completes — the property the
    reference guarantees for per-key ordered embeddings micro-batching
    (``OrderedAsyncBatchExecutor.java:39-97``, used by
    ``ComputeAIEmbeddingsStep.java:72-99``).
    """

    def __init__(
        self,
        batch_size: int,
        processor: AsyncBatchProcessor,
        *,
        buckets: int = 4,
        flush_interval: float = 0.0,
        hash_fn: Optional[Callable[[T], Optional[int]]] = None,
    ) -> None:
        if buckets <= 0:
            raise ValueError("buckets must be > 0")
        self.buckets = buckets
        self.hash_fn = hash_fn
        self._rr = 0
        self._queues: List[asyncio.Queue] = [asyncio.Queue() for _ in range(buckets)]
        self._workers: List[Optional[asyncio.Task]] = [None] * buckets
        self._executors = [
            BatchExecutor(
                batch_size,
                self._make_enqueue(i),
                flush_interval=flush_interval,
            )
            for i in range(buckets)
        ]
        self.processor = processor
        self._closing = False

    def _make_enqueue(self, bucket: int) -> AsyncBatchProcessor:
        async def enqueue(batch: List[T]) -> None:
            self._ensure_worker(bucket)
            await self._queues[bucket].put(batch)

        return enqueue

    def _ensure_worker(self, bucket: int) -> None:
        task = self._workers[bucket]
        if task is None or task.done():
            self._workers[bucket] = asyncio.get_running_loop().create_task(
                self._drain(bucket)
            )

    async def _drain(self, bucket: int) -> None:
        queue = self._queues[bucket]
        while True:
            try:
                batch = queue.get_nowait()
            except asyncio.QueueEmpty:
                if self._closing:
                    return
                try:
                    batch = await asyncio.wait_for(queue.get(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
            await self.processor(batch)
            queue.task_done()

    def _route(self, item: T) -> int:
        if self.hash_fn is not None:
            key_hash = self.hash_fn(item)
            if key_hash is not None:
                return key_hash % self.buckets
        self._rr = (self._rr + 1) % self.buckets
        return self._rr

    async def add(self, item: T) -> None:
        await self._executors[self._route(item)].add(item)

    async def flush(self) -> None:
        for executor in self._executors:
            await executor.flush()
        for queue in self._queues:
            await queue.join()

    async def close(self) -> None:
        await self.flush()
        self._closing = True
        for task in self._workers:
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
