"""The agent runner — the data-plane hot loop.

Re-architecture of the reference's ``AgentRunner``
(``langstream-runtime/langstream-runtime-impl/src/main/java/ai/langstream/runtime/agent/AgentRunner.java:86``):
compose Source → Processor → Sink (defaulting to topic-backed source/sink),
then run the loop: ``source.read()`` → ``processor.process(batch, sink)`` →
per-source-record async ``sink.write()`` → ``source.commit()`` once every
sink write for that source record is durable. Per-record error policy
(retry / skip / fail / dead-letter) mirrors ``StandardErrorsHandler`` +
the retry loops at ``AgentRunner.java:765-889``.

TPU-first re-design notes:

- **Asyncio, one loop**: the reference runs one Java main thread plus async
  completions; here reads, processing, sink writes, metrics, and drain all
  share the event loop. Heavy compute (XLA dispatch) lives on provider-owned
  threads, so the loop stays responsive while the TPU crunches.
- **Reads are pipelined**: the loop keeps reading while earlier records are
  still decoding on the device (the reference behaves the same — its sink
  writes are futures). Backpressure is a bounded pending-record budget
  (``max_pending_records``) instead of unbounded growth; this is what lets
  the completions engine continuously batch across Kafka polls.
- **Commit ordering** is delegated to the topic consumer's contiguous
  watermark (see ``topics/memory.py``), so out-of-order record completion
  never commits past an in-flight record (reference:
  ``SourceRecordTracker.java:32`` + ``KafkaConsumerWrapper.java:52-230``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import (
    Agent,
    AgentContext,
    AgentProcessor,
    AgentService,
    AgentSink,
    AgentSource,
    RecordSink,
    SourceRecordAndResult,
)
from langstream_tpu.api.errors import (
    ErrorHandlingDecision,
    ErrorsSpec,
    FatalAgentError,
    StandardErrorsHandler,
)
from langstream_tpu.api.metrics import MetricsReporter
from langstream_tpu.api.records import Record
from langstream_tpu.api.topics import TopicConnectionsRuntime, TopicConsumer, TopicProducer
from langstream_tpu.runtime.tracing import TRACE_ID_HEADER

logger = logging.getLogger(__name__)


class TopicConsumerSource(AgentSource):
    """Default source: consume the agent's input topic
    (reference: ``TopicConsumerSource.java:28``)."""

    def __init__(
        self,
        consumer: TopicConsumer,
        deadletter_producer: Optional[TopicProducer] = None,
    ) -> None:
        self.consumer = consumer
        self.deadletter_producer = deadletter_producer
        self.agent_id = "topic-consumer-source"
        self.agent_type = "topic-source"

    async def start(self) -> None:
        await self.consumer.start()
        if self.deadletter_producer is not None:
            await self.deadletter_producer.start()

    async def close(self) -> None:
        await self.consumer.close()
        if self.deadletter_producer is not None:
            await self.deadletter_producer.close()

    async def read(self, max_records: int = 100) -> List[Record]:
        return await self.consumer.read(max_records=max_records, timeout=0.2)

    async def commit(self, records: List[Record]) -> None:
        await self.consumer.commit(records)

    async def permanent_failure(self, record: Record, error: BaseException) -> None:
        """Route to the dead-letter topic when available, else crash the
        runner (reference: ``TopicConsumerSource.permanentFailure``)."""
        if self.deadletter_producer is None:
            raise error
        logger.warning("sending record to dead-letter: %r (%s)", record, error)
        await self.deadletter_producer.write(
            record.with_header("langstream-error", str(error)[:1024])
        )

    def agent_info(self) -> Dict[str, Any]:
        info = super().agent_info()
        info["consumed"] = self.consumer.total_out()
        return info


class TopicProducerSink(AgentSink):
    """Default sink: produce to the agent's output topic
    (reference: ``TopicProducerSink.java``)."""

    def __init__(self, producer: TopicProducer) -> None:
        self.producer = producer
        self.agent_id = "topic-producer-sink"
        self.agent_type = "topic-sink"

    async def start(self) -> None:
        await self.producer.start()

    async def close(self) -> None:
        await self.producer.close()

    async def write(self, record: Record) -> None:
        await self.producer.write(record)

    def agent_info(self) -> Dict[str, Any]:
        info = super().agent_info()
        info["produced"] = self.producer.total_in()
        return info


class NullSink(AgentSink):
    """Sink for pipeline-terminal agents with no output topic."""

    agent_id = "null-sink"
    agent_type = "null-sink"

    async def write(self, record: Record) -> None:
        return None


class IdentityProcessor(AgentProcessor):
    """Pass-through processor for source→sink pipelines
    (reference wires the same implicit identity)."""

    agent_id = "identity"
    agent_type = "identity"

    def process(self, records: List[Record], sink: RecordSink) -> None:
        for record in records:
            sink.emit_single(record, [record])


class _QueueRecordSink(RecordSink):
    """Bridges processor emissions into the runner's result queue."""

    def __init__(self) -> None:
        self.queue: "asyncio.Queue[SourceRecordAndResult]" = asyncio.Queue()

    def emit(self, result: SourceRecordAndResult) -> None:
        self.queue.put_nowait(result)


async def process_and_collect(
    processor: AgentProcessor, records: List[Record]
) -> List[SourceRecordAndResult]:
    """Run a batch through an emit-style processor and await all results.

    Utility used by the composite pipeline and tests; the runner itself
    never barriers a batch this way.
    """
    if not records:
        return []
    sink = _QueueRecordSink()
    processor.process(records, sink)
    out: List[SourceRecordAndResult] = []
    for _ in records:
        out.append(await sink.queue.get())
    return out


class RunnerStats:
    def __init__(self) -> None:
        self.records_in = 0
        self.records_out = 0
        self.errors = 0
        self.skipped = 0
        self.dead_lettered = 0
        self.started_at = time.time()

    def snapshot(self) -> Dict[str, Any]:
        return {
            "records-in": self.records_in,
            "records-out": self.records_out,
            "errors": self.errors,
            "skipped": self.skipped,
            "dead-lettered": self.dead_lettered,
            "uptime-s": round(time.time() - self.started_at, 3),
        }


class AgentRunner:
    """Runs one execution-plan node: source → processor → sink.

    Equivalent of ``AgentRunner.runMainLoop`` (``AgentRunner.java:645-724``)
    plus its error-action plumbing (765-889) and graceful drain
    (``waitForNoPendingRecords``, 556-594).
    """

    def __init__(
        self,
        *,
        agent_id: str,
        source: AgentSource,
        processor: AgentProcessor,
        sink: AgentSink,
        errors: ErrorsSpec = ErrorsSpec(),
        context: Optional[AgentContext] = None,
        metrics: Optional[MetricsReporter] = None,
        max_pending_records: int = 512,
        drain_timeout: float = 60.0,
        tracer=None,
    ) -> None:
        from langstream_tpu.runtime.tracing import NOOP
        self.agent_id = agent_id
        self.source = source
        self.processor = processor
        self.sink = sink
        self.errors_spec = errors
        self.context = context or AgentContext(agent_id=agent_id)
        self.metrics = metrics or MetricsReporter(prefix=f"agent_{agent_id}")
        self.max_pending_records = max_pending_records
        self.drain_timeout = drain_timeout
        self.tracer = tracer or NOOP

        self.stats = RunnerStats()
        self._stop = asyncio.Event()
        self._pending = 0
        self._pending_low = asyncio.Event()
        self._pending_low.set()
        self._attempts: Dict[int, int] = {}
        self._result_sink = _QueueRecordSink()
        self._tasks: List[asyncio.Task] = []
        self._failure: Optional[BaseException] = None

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    _agents_started = False

    async def start_agents(self) -> None:
        """Start source/processor/sink. Idempotent; callable before
        :meth:`run` so an orchestrator can bring all replicas into the
        consumer group before any data flows (avoids rebalance churn)."""
        if self._agents_started:
            return
        self._agents_started = True
        for agent in (self.source, self.processor, self.sink):
            await agent.set_context(self.context)
            await agent.start()

    async def _close_agents(self) -> None:
        for agent in (self.sink, self.processor, self.source):
            try:
                await agent.close()
            except Exception:  # noqa: BLE001
                logger.exception("error closing %s", agent)

    def stop(self) -> None:
        """Request a graceful drain-and-exit."""
        self._stop.set()
        self._pending_low.set()  # wake a loop parked on backpressure

    def info(self) -> Dict[str, Any]:
        """``/info`` payload (reference: ``AgentInfoServlet`` +
        ``AgentAPIController`` aggregation)."""
        return {
            "agent-id": self.agent_id,
            "source": self.source.agent_info(),
            "processor": self.processor.agent_info(),
            "sink": self.sink.agent_info(),
            "stats": self.stats.snapshot(),
            "pending-records": self._pending,
        }

    # ------------------------------------------------------------------ #
    # the hot loop
    # ------------------------------------------------------------------ #
    async def _stats_dump_loop(self, interval: float = 30.0) -> None:
        """Periodic one-line stats dump (reference:
        ``AgentRunner.PendingRecordsCounterSource.dumpStats``,
        AgentRunner.java:598-618 — counts + memory every 30 s)."""
        import resource

        while True:
            await asyncio.sleep(interval)
            rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            snapshot = self.stats.snapshot()
            logger.info(
                "agent %s stats: in=%d out=%d errors=%d pending=%d "
                "rss=%.0fMB",
                self.agent_id, snapshot["records-in"],
                snapshot["records-out"], snapshot["errors"],
                self._pending, rss_kb / 1024,
            )

    async def run(self) -> None:
        await self.start_agents()
        stats_dump = asyncio.get_running_loop().create_task(
            self._stats_dump_loop()
        )
        result_worker = asyncio.get_running_loop().create_task(
            self._result_worker()
        )
        try:
            while not self._stop.is_set():
                if self._failure is not None:
                    raise self._failure
                # backpressure: cap in-flight records so a slow device step
                # doesn't buffer the whole topic in memory
                if self._pending >= self.max_pending_records:
                    self._pending_low.clear()
                    await self._pending_low.wait()
                    continue
                budget = self.max_pending_records - self._pending
                with self.tracer.span("source.read", agent=self.agent_id) as read_span:
                    batch = await self.source.read(max_records=budget)
                    if batch:
                        read_span.attributes["records"] = len(batch)
                if not batch:
                    continue
                self.stats.records_in += len(batch)
                self.metrics.counter("records_in").count(len(batch))
                self._pending += len(batch)
                # trace context: tag the dispatch span with the batch's
                # trace id (single-record batches — the gateway/chat hot
                # path — get exact attribution; bigger batches carry the
                # head's id plus the full list as an attribute)
                batch_ids = [
                    str(r.header(TRACE_ID_HEADER)) for r in batch
                    if r.header(TRACE_ID_HEADER)
                ]
                with self.tracer.span(
                    "processor.dispatch", agent=self.agent_id,
                    trace_id=batch_ids[0] if batch_ids else "",
                    records=len(batch),
                    trace_ids=",".join(batch_ids),
                ):
                    self.processor.process(batch, self._result_sink)
            await self._drain()
            if self._failure is not None:
                raise self._failure
        finally:
            stats_dump.cancel()
            result_worker.cancel()
            for task in (stats_dump, result_worker):
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            # cancel any still-running per-record tasks BEFORE closing the
            # agents they write through
            for task in self._tasks:
                if not task.done():
                    task.cancel()
            for task in self._tasks:
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            await self._close_agents()

    async def _drain(self) -> None:
        """Wait for in-flight records before closing (reference:
        ``waitForNoPendingRecords``, ≤60 s). Aborts immediately on a fatal
        failure — the error must propagate, not wait out the drain."""
        deadline = time.time() + self.drain_timeout
        while self._pending > 0 and time.time() < deadline:
            if self._failure is not None:
                return
            await asyncio.sleep(0.01)
        if self._pending > 0:
            logger.warning(
                "drain timeout with %d records still pending", self._pending
            )

    # ------------------------------------------------------------------ #
    # result handling (async, out-of-order)
    # ------------------------------------------------------------------ #
    async def _result_worker(self) -> None:
        while True:
            result = await self._result_sink.queue.get()
            # handle each result concurrently; per-source-record write order
            # is preserved inside _handle_result
            task = asyncio.get_running_loop().create_task(
                self._handle_result(result)
            )
            self._tasks.append(task)
            self._tasks = [t for t in self._tasks if not t.done()]

    def _record_done(self, source_record: Record) -> None:
        self._pending -= 1
        self._attempts.pop(id(source_record), None)
        if self._pending < self.max_pending_records:
            self._pending_low.set()

    async def _handle_result(self, result: SourceRecordAndResult) -> None:
        try:
            if result.error is not None:
                await self._handle_record_error(result.source_record, result.error)
                return
            trace_id = result.source_record.header(TRACE_ID_HEADER) or ""
            records_out = result.result_records
            if trace_id:
                # re-attach the trace id on emitted records so it
                # survives topic hops into downstream agents (processors
                # that rebuild records from scratch would drop it)
                records_out = [
                    record if record.header(TRACE_ID_HEADER)
                    else record.with_header(TRACE_ID_HEADER, trace_id)
                    for record in records_out
                ]
            try:
                with self.tracer.span(
                    "sink.write", trace_id=trace_id, agent=self.agent_id,
                    records=len(records_out),
                ):
                    for record in records_out:
                        await self.sink.write(record)
                        self.stats.records_out += 1
                        self.metrics.counter("records_out").count()
            except BaseException as error:  # noqa: BLE001
                await self._handle_record_error(result.source_record, error)
                return
            with self.tracer.span(
                "source.commit", trace_id=trace_id, agent=self.agent_id
            ):
                await self.source.commit([result.source_record])
            self._record_done(result.source_record)
        except BaseException as error:  # noqa: BLE001 — fatal
            self._failure = error
            self._stop.set()
            self._pending_low.set()

    async def _handle_record_error(
        self, source_record: Record, error: BaseException
    ) -> None:
        """Apply the error policy to one failed source record
        (reference: ``AgentRunner.java:796-889``)."""
        if isinstance(error, FatalAgentError):
            # the agent is gone (e.g. its isolated child process died):
            # retry would hit the same corpse, skip/dead-letter would
            # silently drop every record after it — fail the pod
            raise error
        self.stats.errors += 1
        self.metrics.counter("errors").count()
        attempts = self._attempts.get(id(source_record), 0) + 1
        self._attempts[id(source_record)] = attempts
        handler = StandardErrorsHandler(self.errors_spec)
        decision = handler.handle_error(attempts_for_record=attempts)
        if decision is ErrorHandlingDecision.RETRY:
            logger.info(
                "retrying record after error (attempt %d): %s", attempts, error
            )
            self.processor.process([source_record], self._result_sink)
            return
        if decision is ErrorHandlingDecision.SKIP:
            self.stats.skipped += 1
            await self.source.commit([source_record])
            self._record_done(source_record)
            return
        if decision is ErrorHandlingDecision.DEAD_LETTER:
            try:
                await self.source.permanent_failure(source_record, error)
            except BaseException:
                # no dead-letter support → fail (reference downgrade path)
                raise error
            self.stats.dead_lettered += 1
            await self.source.commit([source_record])
            self._record_done(source_record)
            return
        raise error


class ServiceRunner:
    """Runs a Service agent (no record loop; reference:
    ``AgentService.join``)."""

    def __init__(self, *, agent_id: str, service: AgentService, context=None):
        self.agent_id = agent_id
        self.service = service
        self.context = context or AgentContext(agent_id=agent_id)
        self._stop = asyncio.Event()

    def stop(self) -> None:
        self._stop.set()

    async def run(self) -> None:
        await self.service.set_context(self.context)
        await self.service.start()
        try:
            join_task = asyncio.ensure_future(self.service.join())
            stop_task = asyncio.ensure_future(self._stop.wait())
            await asyncio.wait(
                [join_task, stop_task], return_when=asyncio.FIRST_COMPLETED
            )
            for task in (join_task, stop_task):
                if not task.done():
                    task.cancel()
                    try:
                        await task
                    except asyncio.CancelledError:
                        pass
            if join_task.done() and not join_task.cancelled():
                # a crashed service must propagate, not die silently
                join_task.result()
        finally:
            await self.service.close()
