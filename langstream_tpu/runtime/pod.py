"""Pod entry points — the in-container mains the deployer's manifests run.

Reference parity:

- ``agent-runner``       — ``AgentRunnerStarter.java:39`` reads the mounted
  ``RuntimePodConfiguration`` and runs the agent's main loop, with the
  agent HTTP endpoints (``/info``, ``/metrics``) on :8080
  (``AgentRunner.java:99-113`` Jetty + Prometheus ``DefaultExports``).
- ``code-download``      — ``AgentCodeDownloaderStarter`` /
  ``DownloadAgentCodeCommand``: fetch the app's code archive from code
  storage into the shared emptyDir before the runner starts.
- ``application-setup``  — ``ApplicationSetupRunner.java:40``: create
  topics and deploy assets for the application.
- ``deployer``           — ``RuntimeDeployer.java:40``: build the execution
  plan and write one Agent CR per plan node (the operator turns those into
  StatefulSets).

TPU-native notes: the runner is the same asyncio
:class:`~langstream_tpu.runtime.local.LocalApplicationRunner` used by
``apps run`` — a pod is simply a one-node plan whose replicas come from
the StatefulSet, not from in-process parallelism. The broker is whatever
``streamingCluster`` names (tpulog served broker across pods, Kafka, or
memory for single-pod tests).
"""

from __future__ import annotations

import asyncio
import dataclasses
import io
import json
import logging
import os
import signal
import zipfile
from typing import Any, Dict, Optional

from langstream_tpu.api.errors import ErrorsSpec
from langstream_tpu.compiler.planner import AgentNode, AgentSpec, ExecutionPlan
from langstream_tpu.model.application import (
    Application,
    Instance,
    ResourcesSpec,
)

logger = logging.getLogger(__name__)

AGENT_HTTP_PORT = 8080


# ---------------------------------------------------------------------- #
# pod configuration (the mounted Secret)
# ---------------------------------------------------------------------- #
def load_pod_configuration(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as handle:
        return json.load(handle)


def node_from_document(doc: Dict[str, Any]) -> AgentNode:
    """Rebuild an :class:`AgentNode` from its ``dataclasses.asdict`` form
    (the ``agentNode`` field the operator serializes into Agent CRs and
    pod Secrets)."""

    def spec(value: Optional[Dict[str, Any]]) -> Optional[AgentSpec]:
        if not value:
            return None
        return AgentSpec(
            agent_id=value["agent_id"],
            agent_type=value["agent_type"],
            configuration=value.get("configuration", {}) or {},
        )

    return AgentNode(
        id=doc["id"],
        pipeline=doc.get("pipeline", ""),
        module=doc.get("module", ""),
        source=spec(doc.get("source")),
        processors=[s for s in map(spec, doc.get("processors", [])) if s],
        sink=spec(doc.get("sink")),
        service=spec(doc.get("service")),
        input_topic=doc.get("input_topic"),
        output_topic=doc.get("output_topic"),
        errors=ErrorsSpec(**(doc.get("errors") or {})),
        resources=ResourcesSpec(**(doc.get("resources") or {})),
    )


def _application_for_pod(config: Dict[str, Any]) -> Application:
    """A minimal Application carrying what agents need at runtime:
    AI-provider resources, the streaming cluster, and resolved secrets
    (the pipeline/module structure stays behind in the control plane)."""
    app = Application(
        application_id=config.get("applicationId", "application"),
        tenant=config.get("tenant", "default"),
        resources=config.get("resources", {}) or {},
    )
    app.instance = Instance(
        streaming_cluster=config.get("streamingCluster") or {"type": "memory"},
        compute_cluster={"type": "local"},
        globals_=config.get("globals", {}) or {},
    )
    code_dir = os.environ.get("LANGSTREAM_CODE_DIR")
    if code_dir:
        python_dir = os.path.join(code_dir, "python")
        if os.path.isdir(python_dir):
            app.python_path = python_dir
        elif os.path.isdir(code_dir):
            app.python_path = code_dir
    return app


# ---------------------------------------------------------------------- #
# /metrics + /info HTTP (reference AgentRunner.java:99-113)
# ---------------------------------------------------------------------- #
# the one registry→exposition renderer lives in api.metrics; re-exported
# here because this module is where runner pods (and older call sites)
# import it from
from langstream_tpu.api.metrics import prometheus_text  # noqa: F401,E402


class AgentHttpServer:
    """The per-runner HTTP surface: ``/info`` (JSON), ``/metrics``
    (Prometheus text), ``/ready`` + ``/ok`` (probes)."""

    def __init__(
        self,
        *,
        info: Any,            # () -> dict
        metrics: Any = None,  # MetricsReporter
        gauges: Any = None,   # () -> dict of name -> float
        histograms: Any = None,  # () -> dict of name -> le-snapshot
        port: int = AGENT_HTTP_PORT,
        host: str = "0.0.0.0",
    ) -> None:
        self._info = info
        self._metrics = metrics
        self._gauges = gauges
        self._histograms = histograms
        self.port = port
        self.host = host
        self._runner = None
        self.ready = False

    async def start(self) -> None:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/info", self._handle_info)
        app.router.add_get("/metrics", self._handle_metrics)
        app.router.add_get("/ready", self._handle_ready)
        app.router.add_get("/ok", self._handle_ready)
        app.router.add_get("/debug/profile", self._handle_profile)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._runner = runner
        # port 0 → kernel-assigned; expose the real one for tests
        server = site._server  # noqa: SLF001 — aiohttp has no accessor
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _handle_info(self, request):
        from aiohttp import web

        return web.json_response(self._info())

    async def _handle_metrics(self, request):
        from aiohttp import web

        counters = self._metrics.snapshot() if self._metrics else {}
        gauges = self._gauges() if self._gauges else {}
        histograms: Dict[str, Any] = {}
        if self._metrics is not None and hasattr(
            self._metrics, "histogram_snapshots"
        ):
            histograms.update(self._metrics.histogram_snapshots())
        if self._histograms is not None:
            histograms.update(self._histograms())
        return web.Response(
            text=prometheus_text(counters, gauges, histograms),
            content_type="text/plain",
        )

    async def _handle_ready(self, request):
        from aiohttp import web

        return web.Response(text="OK" if self.ready else "STARTING",
                            status=200 if self.ready else 503)

    async def _handle_profile(self, request):
        """On-demand profiler capture (``?seconds=N``) on runner pods —
        same contract as the OpenAI server's ``/debug/profile``: one
        capture at a time, 409 on a concurrent request."""
        import asyncio as _asyncio

        from aiohttp import web

        from langstream_tpu.runtime import profiling

        try:
            seconds = float(request.query.get("seconds", 3))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "seconds must be a number"}, status=400
            )
        try:
            # capture() validates the range itself (one source of truth)
            path = await _asyncio.to_thread(profiling.capture, seconds)
        except ValueError as error:
            return web.json_response({"error": str(error)}, status=400)
        except profiling.ProfileBusyError as error:
            return web.json_response({"error": str(error)}, status=409)
        return web.json_response({"path": path, "seconds": seconds})


# ---------------------------------------------------------------------- #
# agent-runner
# ---------------------------------------------------------------------- #
async def agent_runner_main(
    config_path: str,
    *,
    http_port: int = AGENT_HTTP_PORT,
    stop_event: Optional[asyncio.Event] = None,
) -> None:
    """Run one execution-plan node until SIGTERM, serving /info+/metrics.

    Reference: ``AgentRunnerStarter.java:39`` → ``AgentRunner.run``.
    """
    from langstream_tpu.runtime.local import LocalApplicationRunner

    # pods can override the port via env without changing the manifest
    # command line (tests use this to avoid :8080 collisions)
    http_port = int(os.environ.get("LANGSTREAM_HTTP_PORT", http_port))
    plugins_dir = os.environ.get("LANGSTREAM_PLUGINS_DIR")
    if plugins_dir:
        from langstream_tpu.runtime.plugins import load_plugins

        load_plugins(plugins_dir)
    # observability: pods opt into the flight recorder via
    # LANGSTREAM_FLIGHT_DIR (trace dumps likewise via
    # LANGSTREAM_TRACE_DIR, handled by the tracer registry)
    from langstream_tpu.runtime import flight

    flight.configure_from_env()
    flight.record("phase", name="pod-start", config=config_path)
    # multi-host slice: all pods of this replica enter one pjit program
    # (SURVEY §7 hard part (e)); a no-op for single-host replicas
    from langstream_tpu.runtime.multihost import initialize_multihost

    initialize_multihost()
    config = load_pod_configuration(config_path)
    node = node_from_document(config["agentNode"])
    # one pod = one replica; data parallelism is the StatefulSet's
    # replica count (all replicas share one consumer group)
    node = dataclasses.replace(
        node, resources=dataclasses.replace(node.resources, parallelism=1)
    )
    application = _application_for_pod(config)
    plan = ExecutionPlan(application=application, topics={}, agents=[node])
    state_dir = os.environ.get("LANGSTREAM_STATE_DIR")
    if state_dir:
        os.makedirs(state_dir, exist_ok=True)
    runner = LocalApplicationRunner(plan, state_directory=state_dir or None)

    def _engine_module():
        import sys

        return sys.modules.get("langstream_tpu.providers.jax_local.engine")

    def gauges() -> Dict[str, float]:
        # TPU engine internals, when this pod hosts a jax-local engine
        module = _engine_module()
        return module.engines_snapshot() if module else {}

    def histograms() -> Dict[str, Any]:
        module = _engine_module()
        return module.engines_histograms() if module else {}

    http = AgentHttpServer(
        info=runner.info, metrics=runner.metrics, gauges=gauges,
        histograms=histograms, port=http_port,
    )
    await http.start()
    logger.info(
        "agent-runner %s serving /info,/metrics on :%d", node.id, http.port
    )

    stop = stop_event or asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):  # non-main thread
            pass
    try:
        await runner.start()
        http.ready = True
        join = asyncio.ensure_future(runner.join())
        stop_task = asyncio.ensure_future(stop.wait())
        await asyncio.wait(
            [join, stop_task], return_when=asyncio.FIRST_COMPLETED
        )
        for task in (join, stop_task):
            if not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        if join.done() and not join.cancelled():
            join.result()  # propagate a crashed runner
    finally:
        http.ready = False
        await runner.stop()
        await http.stop()


# ---------------------------------------------------------------------- #
# code-download
# ---------------------------------------------------------------------- #
def code_download_main(config_path: str, target: str) -> None:
    """Fetch + unpack the application's code archive (init container).

    Reference: ``AgentCodeDownloaderStarter`` — the runner pod's code
    volume is populated before the main container starts.
    """
    from langstream_tpu.controlplane.codestorage import create_code_storage

    config = load_pod_configuration(config_path)
    code_id = config.get("codeArchiveId")
    tenant = config.get("tenant", "default")
    os.makedirs(target, exist_ok=True)
    if not code_id:
        logger.info("no code archive for this application; nothing to do")
        return
    storage_config = json.loads(
        os.environ.get("LANGSTREAM_CODE_STORAGE") or "{}"
    )
    storage = create_code_storage(storage_config)
    archive = storage.download(tenant, code_id)
    with zipfile.ZipFile(io.BytesIO(archive)) as zf:
        for member in zf.namelist():
            # refuse path traversal out of the target dir
            path = os.path.realpath(os.path.join(target, member))
            if not path.startswith(os.path.realpath(target) + os.sep):
                raise ValueError(f"archive member escapes target: {member}")
        zf.extractall(target)
    logger.info("downloaded code archive %s into %s", code_id, target)


# ---------------------------------------------------------------------- #
# application-setup
# ---------------------------------------------------------------------- #
def _application_from_env() -> Application:
    """Parse the Application CR spec the Jobs receive via
    ``LANGSTREAM_APPLICATION`` (see ``deployer/resources.py:_job``)."""
    raw = os.environ.get("LANGSTREAM_APPLICATION")
    if not raw:
        raise SystemExit("LANGSTREAM_APPLICATION env var is required")
    spec = json.loads(raw)
    definition = spec.get("application")
    instance = spec.get("instance")
    if isinstance(definition, str):
        definition = json.loads(definition or "{}")
    if isinstance(instance, str):
        instance = json.loads(instance or "{}")
    application = Application.from_document(definition or {}, instance or {})
    if spec.get("applicationId"):
        application.application_id = spec["applicationId"]
    if spec.get("tenant"):
        application.tenant = spec["tenant"]
    return application


async def application_setup_main(*, delete: bool = False) -> None:
    """Create (or clean up) topics and assets for the application.

    Reference: ``ApplicationSetupRunner.java:40`` (runApplicationSetup:
    topics + assets; cleanup path on delete).
    """
    from langstream_tpu.api.assets import deploy_assets
    from langstream_tpu.compiler.planner import build_execution_plan
    from langstream_tpu.topics import create_topic_runtime

    application = _application_from_env()
    plan = build_execution_plan(application)
    runtime = create_topic_runtime(application.instance.streaming_cluster)
    admin = runtime.create_admin()
    try:
        for spec in plan.topics.values():
            if delete:
                if spec.deletion_mode == "delete":
                    await admin.delete_topic(spec.name)
            elif spec.creation_mode == "create-if-not-exists":
                await admin.create_topic(spec)
                logger.info("topic %s ready", spec.name)
    finally:
        await admin.close()
        await runtime.close()
    if plan.assets and not delete:
        await deploy_assets(plan.assets, application.resources)
        logger.info("deployed %d assets", len(plan.assets))


# ---------------------------------------------------------------------- #
# deployer
# ---------------------------------------------------------------------- #
async def deployer_main(*, delete: bool = False) -> None:
    """Build the execution plan and write Agent CRs (the operator turns
    them into StatefulSets). Reference: ``RuntimeDeployer.java:40``.
    """
    from langstream_tpu.deployer.crds import AgentCustomResource
    from langstream_tpu.deployer.kubeclient import create_kube_api
    from langstream_tpu.compiler.planner import build_execution_plan

    raw = os.environ.get("LANGSTREAM_APPLICATION")
    spec = json.loads(raw) if raw else {}
    application = _application_from_env()
    namespace = application.tenant or "default"
    kube = create_kube_api()
    label = {"langstream.tpu/application": application.application_id}
    if delete:
        for doc in kube.list("Agent", namespace, label):
            kube.delete("Agent", namespace, doc["metadata"]["name"])
        return
    plan = build_execution_plan(application)
    desired = set()
    for node in plan.agents:
        name = f"{application.application_id}-{node.id}"
        desired.add(name)
        cr = AgentCustomResource(
            name=name,
            namespace=namespace,
            application_id=application.application_id,
            agent_node=dataclasses.asdict(node),
            streaming_cluster=application.instance.streaming_cluster,
            resources=application.resources,
            parallelism=node.resources.parallelism,
            size=node.resources.size,
            disk=node.resources.disk,
            code_archive_id=spec.get("codeArchiveId"),
            checksum=spec.get("checksum"),
        )
        kube.apply(cr.to_manifest())
        logger.info("applied Agent CR %s", name)
    for doc in kube.list("Agent", namespace, label):
        if doc["metadata"]["name"] not in desired:
            kube.delete("Agent", namespace, doc["metadata"]["name"])
