"""Multi-host DCN mesh bootstrap for agent-runner pods.

SURVEY §7 hard part (e): a TPU slice larger than one host (v5e > 8
chips) runs one replica across several pods; every pod of the replica
must enter the same pjit program, which requires
``jax.distributed.initialize`` with a shared coordinator and a stable
process id. The operator's StatefulSet provides the ingredients
(reference-side analogue is GKE's JobSet/TPU webhook; the reference
itself never spans a model across processes):

- ``podManagementPolicy: Parallel`` + a headless service → every pod
  has a stable DNS name ``{sts}-{ordinal}.{sts}.{ns}.svc``.
- ``LANGSTREAM_HOSTS_PER_REPLICA`` (H): pods ``r*H .. r*H+H-1`` form
  data-parallel replica ``r``; within it, the pod with local rank 0 is
  the jax coordinator.

``plan_from_statefulset`` derives (replica, process id, coordinator)
from the pod's own hostname, and :func:`initialize_multihost` applies
it. Single-host replicas (H == 1) are a no-op.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import Optional

logger = logging.getLogger(__name__)

_ORDINAL = re.compile(r"^(?P<base>.+)-(?P<ordinal>\d+)$")


@dataclasses.dataclass(frozen=True)
class MultihostPlan:
    replica: int            # data-parallel replica this pod belongs to
    process_id: int         # jax process id within the replica (0..H-1)
    num_processes: int      # H
    coordinator: str        # host:port of the replica's rank-0 pod

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def plan_from_statefulset(
    hostname: Optional[str] = None,
    *,
    hosts_per_replica: Optional[int] = None,
    namespace: Optional[str] = None,
    service: Optional[str] = None,
    port: int = 8476,
) -> Optional[MultihostPlan]:
    """Derive the jax.distributed topology from StatefulSet identity.

    Returns None when this pod is a single-host replica (H <= 1) or is
    not running under a StatefulSet-shaped hostname.
    """
    hosts = int(
        hosts_per_replica
        if hosts_per_replica is not None
        else os.environ.get("LANGSTREAM_HOSTS_PER_REPLICA", "1")
    )
    if hosts <= 1:
        return None
    hostname = hostname or os.environ.get("HOSTNAME", "")
    match = _ORDINAL.match(hostname)
    if not match:
        raise ValueError(
            f"multi-host replica needs a StatefulSet ordinal hostname, "
            f"got {hostname!r}"
        )
    base = match.group("base")
    ordinal = int(match.group("ordinal"))
    replica, process_id = divmod(ordinal, hosts)
    namespace = namespace or os.environ.get(
        "LANGSTREAM_NAMESPACE", "default"
    )
    # the headless service shares the StatefulSet's name
    # (deployer/resources.py generate_headless_service)
    service = service or base
    coordinator_pod = f"{base}-{replica * hosts}"
    coordinator = (
        f"{coordinator_pod}.{service}.{namespace}.svc:{port}"
    )
    return MultihostPlan(
        replica=replica,
        process_id=process_id,
        num_processes=hosts,
        coordinator=coordinator,
    )


def initialize_multihost(plan: Optional[MultihostPlan] = None) -> bool:
    """Bring up jax.distributed for this pod's replica when needed.
    Returns True when distributed init ran."""
    if plan is None:
        plan = plan_from_statefulset()
    if plan is None:
        return False
    import jax

    logger.info(
        "multi-host replica %d: process %d/%d, coordinator %s",
        plan.replica, plan.process_id, plan.num_processes, plan.coordinator,
    )
    jax.distributed.initialize(
        coordinator_address=plan.coordinator,
        num_processes=plan.num_processes,
        process_id=plan.process_id,
    )
    return True
