"""Single-process application runner.

Equivalent of the reference's runtime-tester "mini cluster"
(``langstream-runtime/langstream-runtime-tester/src/main/java/ai/langstream/runtime/tester/LocalApplicationRunner.java:56``
— deploy 123-143, executeAgentRunners 173) which powers ``langstream docker
run``: deploy an execution plan in one process — create topics, start one
:class:`AgentRunner` task per agent-node replica, share a single in-process
broker — and drain gracefully on stop.

This is also the integration-test harness for everything above it, mirroring
the reference's test strategy (``AbstractApplicationRunner.java:58``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import tempfile
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import AgentContext
from langstream_tpu.api.errors import FailureAction
from langstream_tpu.api.metrics import MetricsReporter
from langstream_tpu.api.topics import TopicConnectionsRuntime
from langstream_tpu.compiler.planner import AgentNode, ExecutionPlan
from langstream_tpu.runtime.composite import CompositeAgentProcessor
from langstream_tpu.runtime.registry import create_agent
from langstream_tpu.runtime.runner import (
    AgentRunner,
    IdentityProcessor,
    NullSink,
    ServiceRunner,
    TopicConsumerSource,
    TopicProducerSink,
)
from langstream_tpu.topics import create_topic_runtime

logger = logging.getLogger(__name__)


class LocalApplicationRunner:
    """Deploys and runs an :class:`ExecutionPlan` in-process."""

    def __init__(
        self,
        plan: ExecutionPlan,
        *,
        topic_runtime: Optional[TopicConnectionsRuntime] = None,
        state_directory: Optional[str] = None,
        tracer=None,
    ) -> None:
        from langstream_tpu.runtime.tracing import get_tracer

        self.plan = plan
        # default to the process-wide runner tracer: a NOOP unless
        # LANGSTREAM_TRACE_DIR is set, in which case every pod/apps-run
        # leaves a Chrome-trace dump for `langstream-tpu trace` to merge
        self.tracer = tracer if tracer is not None else get_tracer("runner")
        self.application = plan.application
        self.topic_runtime = topic_runtime or create_topic_runtime(
            plan.application.instance.streaming_cluster
        )
        self.state_directory = state_directory or tempfile.mkdtemp(
            prefix="langstream-state-"
        )
        self.metrics = MetricsReporter()
        self.runners: List[Any] = []
        self._tasks: List[asyncio.Task] = []
        self._started = asyncio.Event()
        # one provider registry per app: all agents share the same device
        # engines (one model, one mesh, one KV cache pool per resource)
        from langstream_tpu.providers.registry import ServiceProviderRegistry

        self._service_provider_registry = ServiceProviderRegistry(
            self.application.resources
        )

    # ------------------------------------------------------------------ #
    # deploy (reference: ApplicationSetupRunner topics/assets setup)
    # ------------------------------------------------------------------ #
    async def setup(self) -> None:
        admin = self.topic_runtime.create_admin()
        for spec in self.plan.topics.values():
            if spec.creation_mode == "create-if-not-exists":
                await admin.create_topic(spec)
        await admin.close()
        if self.plan.assets:
            from langstream_tpu.api.assets import deploy_assets

            await deploy_assets(self.plan.assets, self.application.resources)

    def _make_context(self, node: AgentNode, replica: int) -> AgentContext:
        state_dir = os.path.join(self.state_directory, node.id, str(replica))
        os.makedirs(state_dir, exist_ok=True)
        return AgentContext(
            agent_id=node.id,
            application_id=self.application.application_id,
            tenant=self.application.tenant,
            topic_connections=self.topic_runtime,
            persistent_state_directory=state_dir,
            metrics=self.metrics.with_prefix(f"agent_{node.id.replace('-', '_')}"),
            global_agent_id=f"{self.application.application_id}-{node.id}",
            service_provider_registry=self._service_provider_registry,
            resources=self.application.resources,
        )

    async def _build_agent(self, spec, context: AgentContext):
        agent = create_agent(spec.agent_type)
        agent.agent_id = spec.agent_id
        configuration = spec.configuration
        if spec.agent_type.startswith("python-") and self.application.python_path:
            configuration = dict(configuration)
            paths = list(configuration.get("pythonPath", []))
            for sub in ("", "lib"):
                path = os.path.join(self.application.python_path, sub).rstrip("/")
                if path not in paths and os.path.isdir(path):
                    paths.append(path)
            configuration["pythonPath"] = paths
        await agent.init(configuration)
        return agent

    async def _build_runner(self, node: AgentNode, replica: int):
        context = self._make_context(node, replica)
        if node.service is not None:
            service = await self._build_agent(node.service, context)
            return ServiceRunner(
                agent_id=node.id, service=service, context=context
            )

        # source
        if node.source is not None:
            source = await self._build_agent(node.source, context)
        else:
            assert node.input_topic is not None
            group = f"{self.application.application_id}-{node.id}"
            consumer = self.topic_runtime.create_consumer(
                node.id, {"topic": node.input_topic, "group": group}
            )
            deadletter = None
            if node.errors.resolved_action() is FailureAction.DEAD_LETTER:
                deadletter = self.topic_runtime.create_deadletter_producer(
                    node.id, {"topic": node.input_topic}
                )
            source = TopicConsumerSource(consumer, deadletter)

        # processor chain
        processors = []
        for spec in node.processors:
            processors.append(await self._build_agent(spec, context))
        if not processors:
            processor = IdentityProcessor()
        elif len(processors) == 1:
            processor = processors[0]
        else:
            processor = CompositeAgentProcessor(processors)
            processor.agent_id = node.id

        # sink
        if node.sink is not None:
            sink = await self._build_agent(node.sink, context)
        elif node.output_topic is not None:
            producer_config: Dict[str, Any] = {"topic": node.output_topic}
            topic_spec = self.plan.topics.get(node.output_topic)
            if topic_spec is not None and topic_spec.schema:
                # declared topic schema flows to the producer (avro
                # interop on schema-aware runtimes)
                producer_config["schema"] = topic_spec.schema
            producer = self.topic_runtime.create_producer(
                node.id, producer_config
            )
            sink = TopicProducerSink(producer)
        else:
            sink = NullSink()

        return AgentRunner(
            agent_id=f"{node.id}-{replica}" if node.resources.parallelism > 1 else node.id,
            source=source,
            processor=processor,
            sink=sink,
            errors=node.errors,
            context=context,
            metrics=context.metrics,
            tracer=self.tracer,
        )

    # ------------------------------------------------------------------ #
    # run lifecycle
    # ------------------------------------------------------------------ #
    async def start(self) -> None:
        """Setup topics and launch every node replica
        (reference: ``executeAgentRunners``, LocalApplicationRunner.java:173)."""
        await self.setup()
        loop = asyncio.get_running_loop()
        for node in self.plan.agents:
            for replica in range(max(1, node.resources.parallelism)):
                runner = await self._build_runner(node, replica)
                self.runners.append(runner)
        # bring every replica's agents (and consumer-group membership) up
        # BEFORE any loop runs — and CONCURRENTLY, so all members of a
        # group land in one rebalance generation (a sequential bring-up
        # makes each later member wait out a full rebalance window while
        # the earlier ones aren't polling yet). On any failure, close
        # everything that DID start: a leaked consumer's heartbeat task
        # would hold its group membership (and partitions) alive forever
        results = await asyncio.gather(
            *[
                runner.start_agents()
                for runner in self.runners
                if hasattr(runner, "start_agents")
            ],
            return_exceptions=True,
        )
        failure = next(
            (r for r in results if isinstance(r, BaseException)), None
        )
        if failure is not None:
            for runner in self.runners:
                if not hasattr(runner, "_close_agents"):
                    continue
                try:
                    await runner._close_agents()  # noqa: SLF001
                except Exception:  # noqa: BLE001
                    logger.exception("cleanup after failed start")
            await self._service_provider_registry.close()
            await self.topic_runtime.close()
            raise failure
        for runner in self.runners:
            task = loop.create_task(runner.run())
            # surface a crashed runner the moment it dies: without this
            # the failure sits unretrieved until stop()/join(), and a
            # gateway client whose pipeline just vanished hangs with no
            # log line anywhere (seen: an over-long prompt rejected by
            # the engine under the default fail policy)
            task.add_done_callback(self._log_runner_exit)
            self._tasks.append(task)
        self._started.set()

    @staticmethod
    def _log_runner_exit(task: "asyncio.Task") -> None:
        if task.cancelled():
            return
        error = task.exception()
        if error is not None:
            logger.error(
                "agent runner crashed — records in flight are lost and "
                "gateway consumers of its topics will stall",
                exc_info=error,
            )

    async def stop(self, timeout: float = 30.0) -> None:
        for runner in self.runners:
            runner.stop()
        failure = None
        if self._tasks:
            done, pending = await asyncio.wait(self._tasks, timeout=timeout)
            for task in pending:
                task.cancel()
            for task in done:
                error = task.exception()
                if error is not None and failure is None:
                    failure = error
        # always release engines/brokers, even when a runner died — the
        # engine thread and device HBM must not outlive the app
        await self._service_provider_registry.close()
        await self.topic_runtime.close()
        if failure is not None:
            raise failure

    async def join(self) -> None:
        """Wait until any runner fails (propagates) or all complete."""
        if not self._tasks:
            return
        done, _pending = await asyncio.wait(
            self._tasks, return_when=asyncio.FIRST_EXCEPTION
        )
        for task in done:
            error = task.exception()
            if error is not None:
                raise error

    def info(self) -> Dict[str, Any]:
        return {
            "application-id": self.application.application_id,
            "agents": [
                runner.info() if hasattr(runner, "info") else {"agent-id": runner.agent_id}
                for runner in self.runners
            ],
            "topics": sorted(self.plan.topics),
        }

    # convenience for tests & the gateway
    def producer(self, topic: str):
        return self.topic_runtime.create_producer("external", {"topic": topic})

    def reader(self, topic: str, position=None):
        from langstream_tpu.api.topics import OffsetPosition

        return self.topic_runtime.create_reader(
            {"topic": topic}, position or OffsetPosition.EARLIEST
        )


async def run_application(
    app_dir: str,
    *,
    instance_file: Optional[str] = None,
    secrets_file: Optional[str] = None,
    tracer=None,
) -> LocalApplicationRunner:
    """Parse, plan, and start an application directory (the ``docker run``
    path, ``langstream-cli/.../docker/LocalRunApplicationCmd.java:56``)."""
    from langstream_tpu.compiler import build_application, build_execution_plan

    plugins_dir = os.environ.get("LANGSTREAM_PLUGINS_DIR")
    if plugins_dir:
        from langstream_tpu.runtime.plugins import load_plugins

        load_plugins(plugins_dir)
    application = build_application(
        app_dir, instance_file=instance_file, secrets_file=secrets_file
    )
    plan = build_execution_plan(application)
    runner = LocalApplicationRunner(plan, tracer=tracer)
    await runner.start()
    return runner
