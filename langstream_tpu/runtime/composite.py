"""Composite (fused) agent execution.

Equivalent of the reference's ``CompositeAgentProcessor``
(``langstream-runtime/langstream-runtime-impl/src/main/java/ai/langstream/runtime/agent/CompositeAgentProcessor.java:36``):
when the planner fuses consecutive composable agents into one
``composite-agent`` node, this processor runs the chained pipeline inside a
single runner, passing records in memory between steps — eliminating the
broker hop that would otherwise sit between every agent.

Chaining preserves the emit-as-you-complete contract: each *source* record
flows through the whole chain in its own task, so one slow record (e.g. a
long decode) never barriers its batch-mates.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

from langstream_tpu.api.agent import (
    AgentContext,
    AgentProcessor,
    RecordSink,
    SourceRecordAndResult,
)
from langstream_tpu.api.records import Record
from langstream_tpu.runtime.registry import create_agent


async def process_one(
    processor: AgentProcessor, record: Record
) -> SourceRecordAndResult:
    """Run one record through an emit-style processor and await its result."""
    from langstream_tpu.runtime.runner import process_and_collect

    return (await process_and_collect(processor, [record]))[0]


class CompositeAgentProcessor(AgentProcessor):
    """Chains N processors; configured with the fused agents' configs
    (reference config parse: ``CompositeAgentProcessor.java:52-75``)."""

    agent_type = "composite-agent"

    def __init__(self, processors: Optional[List[AgentProcessor]] = None) -> None:
        self.processors: List[AgentProcessor] = processors or []
        self.agent_id = "composite"

    async def init(self, configuration: Dict[str, Any]) -> None:
        """Build sub-processors from a ``processors: [{agentType, agentId,
        configuration}]`` list when not injected programmatically."""
        for spec in configuration.get("processors", []):
            processor = create_agent(spec["agentType"])
            processor.agent_id = spec.get("agentId", spec["agentType"])
            await processor.init(spec.get("configuration", {}))
            self.processors.append(processor)

    async def set_context(self, context: AgentContext) -> None:
        self.context = context
        for processor in self.processors:
            await processor.set_context(context)

    async def start(self) -> None:
        for processor in self.processors:
            await processor.start()

    async def close(self) -> None:
        for processor in self.processors:
            await processor.close()

    def agent_info(self) -> Dict[str, Any]:
        return {
            "agent-id": self.agent_id,
            "agent-type": self.agent_type,
            "component-type": "processor",
            "processors": [p.agent_info() for p in self.processors],
        }

    def process(self, records: List[Record], sink: RecordSink) -> None:
        loop = asyncio.get_running_loop()
        for record in records:
            loop.create_task(self._run_chain(record, sink))

    async def _run_chain(self, source_record: Record, sink: RecordSink) -> None:
        current = [source_record]
        try:
            for processor in self.processors:
                next_records: List[Record] = []
                for record in current:
                    result = await process_one(processor, record)
                    if result.error is not None:
                        raise result.error
                    next_records.extend(result.result_records)
                current = next_records
                if not current:
                    break
            sink.emit_single(source_record, current)
        except BaseException as error:  # noqa: BLE001 — routed to policy
            sink.emit_error(source_record, error)
