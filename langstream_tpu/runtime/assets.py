"""Built-in asset managers: SQL tables and vector collections.

TPU-build counterparts of the reference's asset providers
(``langstream-core/src/main/java/ai/langstream/impl/assets/``:
JdbcAssetsProvider, CassandraAssetsProvider, MilvusAssetsProvider,
OpenSearchAssetsProvider, SolrAssetsProvider). The local build ships
managers for its bundled datasources:

- ``jdbc-table`` / ``table`` — run ``create-statements`` against the
  SQL datasource named by ``datasource`` (sqlite locally; the config
  shape matches the reference's jdbc-table asset).
- ``vector-collection`` — create a named in-process vector store
  collection with the given ``dimensions``.

External systems register via
:func:`langstream_tpu.api.assets.register_asset_manager`.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from langstream_tpu.api.assets import AssetManager, register_asset_manager
from langstream_tpu.agents.datasource import DataSourceRegistry

logger = logging.getLogger(__name__)


def _datasource_name(config: Dict[str, Any]) -> str:
    value = config.get("datasource")
    if isinstance(value, dict):
        # the reference injects the full resource here; accept both
        return value.get("name") or value.get("id") or "datasource"
    return value


class JdbcTableAssetManager(AssetManager):
    """``jdbc-table`` (reference: JdbcAssetsProvider — table-name +
    create-statements + optional delete-statements)."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        self._registry = DataSourceRegistry(resources)
        self._source = self._registry.resolve(_datasource_name(asset.config))
        self.table = asset.config.get("table-name") or asset.name

    async def asset_exists(self) -> bool:
        rows = await self._source.query(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?",
            [self.table],
        )
        return bool(rows)

    async def deploy_asset(self) -> None:
        statements: List[str] = self.asset.config.get("create-statements", [])
        if not statements:
            raise ValueError(
                f"asset {self.asset.name!r}: jdbc-table needs create-statements"
            )
        for statement in statements:
            await self._source.execute(statement, [])

    async def delete_asset(self) -> bool:
        statements = self.asset.config.get("delete-statements") or [
            f"DROP TABLE IF EXISTS {self.table}"
        ]
        for statement in statements:
            await self._source.execute(statement, [])
        return True


class VectorCollectionAssetManager(AssetManager):
    """``vector-collection``: a named collection in the in-process
    vector store (role analogue of milvus-collection / opensearch-index
    assets)."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        from langstream_tpu.agents import vectorstore

        self._module = vectorstore
        self.collection = asset.config.get("collection-name") or asset.name
        self.dimensions = int(asset.config.get("dimensions", 0) or 0)

    async def asset_exists(self) -> bool:
        return self.collection in getattr(self._module, "_SHARED_STORES", {})

    async def deploy_asset(self) -> None:
        if not self.dimensions:
            raise ValueError(
                f"asset {self.asset.name!r}: vector-collection needs dimensions"
            )
        self._module.shared_store(self.collection, self.dimensions)

    async def delete_asset(self) -> bool:
        shared = getattr(self._module, "_SHARED_STORES", {})
        return shared.pop(self.collection, None) is not None


register_asset_manager("jdbc-table", JdbcTableAssetManager)
register_asset_manager("table", JdbcTableAssetManager)
register_asset_manager("vector-collection", VectorCollectionAssetManager)
