"""Built-in asset managers: SQL tables and vector collections.

TPU-build counterparts of the reference's asset providers
(``langstream-core/src/main/java/ai/langstream/impl/assets/``:
JdbcAssetsProvider, CassandraAssetsProvider, MilvusAssetsProvider,
OpenSearchAssetsProvider, SolrAssetsProvider). The local build ships
managers for its bundled datasources:

- ``jdbc-table`` / ``table`` — run ``create-statements`` against the
  SQL datasource named by ``datasource`` (sqlite locally; the config
  shape matches the reference's jdbc-table asset).
- ``vector-collection`` — create a named in-process vector store
  collection with the given ``dimensions``.

External systems register via
:func:`langstream_tpu.api.assets.register_asset_manager`.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from langstream_tpu.api.assets import AssetManager, register_asset_manager
from langstream_tpu.agents.datasource import DataSourceRegistry

logger = logging.getLogger(__name__)


def _datasource_name(config: Dict[str, Any]) -> str:
    value = config.get("datasource")
    if isinstance(value, dict):
        # the reference injects the full resource here; accept both
        return value.get("name") or value.get("id") or "datasource"
    return value


class JdbcTableAssetManager(AssetManager):
    """``jdbc-table`` (reference: JdbcAssetsProvider — table-name +
    create-statements + optional delete-statements)."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        self._registry = DataSourceRegistry(resources)
        self._source = self._registry.resolve(_datasource_name(asset.config))
        self.table = asset.config.get("table-name") or asset.name

    async def asset_exists(self) -> bool:
        rows = await self._source.query(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?",
            [self.table],
        )
        return bool(rows)

    async def deploy_asset(self) -> None:
        statements: List[str] = self.asset.config.get("create-statements", [])
        if not statements:
            raise ValueError(
                f"asset {self.asset.name!r}: jdbc-table needs create-statements"
            )
        for statement in statements:
            await self._source.execute(statement, [])

    async def delete_asset(self) -> bool:
        statements = self.asset.config.get("delete-statements") or [
            f"DROP TABLE IF EXISTS {self.table}"
        ]
        for statement in statements:
            await self._source.execute(statement, [])
        return True


class VectorCollectionAssetManager(AssetManager):
    """``vector-collection``: a named collection in the in-process
    vector store (role analogue of milvus-collection / opensearch-index
    assets)."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        from langstream_tpu.agents import vectorstore

        self._module = vectorstore
        self.collection = asset.config.get("collection-name") or asset.name
        self.dimensions = int(asset.config.get("dimensions", 0) or 0)

    async def asset_exists(self) -> bool:
        return self.collection in getattr(self._module, "_SHARED_STORES", {})

    async def deploy_asset(self) -> None:
        if not self.dimensions:
            raise ValueError(
                f"asset {self.asset.name!r}: vector-collection needs dimensions"
            )
        self._module.shared_store(self.collection, self.dimensions)

    async def delete_asset(self) -> bool:
        shared = getattr(self._module, "_SHARED_STORES", {})
        return shared.pop(self.collection, None) is not None


register_asset_manager("jdbc-table", JdbcTableAssetManager)
register_asset_manager("table", JdbcTableAssetManager)
register_asset_manager("vector-collection", VectorCollectionAssetManager)


class OpenSearchIndexAssetManager(AssetManager):
    """``opensearch-index`` (reference: OpenSearchAssetsProvider —
    ``datasource`` + optional ``mappings``/``settings`` JSON): create or
    delete the datasource's index over the REST API."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        self._registry = DataSourceRegistry(resources)
        self._source = self._registry.resolve(_datasource_name(asset.config))

    async def close(self) -> None:
        await self._registry.close()

    @staticmethod
    def _absent(error: IOError) -> bool:
        """Only a 404 means 'no such index'; auth/5xx/connection
        failures must surface, not masquerade as absence."""
        return "HTTP 404" in str(error)

    async def asset_exists(self) -> bool:
        try:
            await self._source._call(
                "GET", f"{self._source.endpoint}/{self._source.index}"
            )
            return True
        except IOError as error:
            if self._absent(error):
                return False
            raise

    async def deploy_asset(self) -> None:
        import json as _json

        body: Dict[str, Any] = {}
        for key in ("mappings", "settings"):
            value = self.asset.config.get(key)
            if value:
                body[key] = (
                    _json.loads(value) if isinstance(value, str) else value
                )
        await self._source._call(
            "PUT", f"{self._source.endpoint}/{self._source.index}",
            body or None,
        )

    async def delete_asset(self) -> bool:
        try:
            await self._source._call(
                "DELETE", f"{self._source.endpoint}/{self._source.index}"
            )
            return True
        except IOError as error:
            if self._absent(error):
                return False
            raise


class MilvusCollectionAssetManager(AssetManager):
    """``milvus-collection`` (reference: MilvusAssetsProvider —
    ``collection-name`` + ``create-statements``, each a JSON command for
    the collection API; v2 REST spelling here)."""

    async def init(self, asset, resources) -> None:
        await super().init(asset, resources)
        self._registry = DataSourceRegistry(resources)
        self._source = self._registry.resolve(_datasource_name(asset.config))
        self.collection = (
            asset.config.get("collection-name") or asset.name
        )

    async def close(self) -> None:
        await self._registry.close()

    async def _collections(self, op: str, body: Dict[str, Any]):
        return await self._source._v2(op, body, group="collections")

    async def asset_exists(self) -> bool:
        payload = await self._collections(
            "has", {"collectionName": self.collection}
        )
        return bool((payload.get("data") or {}).get("has"))

    async def deploy_asset(self) -> None:
        import json as _json

        statements = self.asset.config.get("create-statements") or []
        if statements:
            for statement in statements:
                body = (
                    _json.loads(statement)
                    if isinstance(statement, str) else dict(statement)
                )
                body.setdefault("collectionName", self.collection)
                await self._collections("create", body)
            return
        dimension = int(self.asset.config.get("dimensions", 0) or 0)
        if not dimension:
            raise ValueError(
                f"asset {self.asset.name!r}: milvus-collection needs "
                "create-statements or dimensions"
            )
        await self._collections("create", {
            "collectionName": self.collection, "dimension": dimension,
        })

    async def delete_asset(self) -> bool:
        try:
            await self._collections(
                "drop", {"collectionName": self.collection}
            )
            return True
        except IOError:
            # drop of a missing collection must not abort the cleanup
            # loop over the remaining assets
            logger.info("milvus collection %s not dropped", self.collection)
            return False


register_asset_manager("opensearch-index", OpenSearchIndexAssetManager)
register_asset_manager("milvus-collection", MilvusCollectionAssetManager)
