"""Decode-stall watchdog: notice a degraded or wedged engine and capture
evidence automatically, instead of waiting for a human to read a flight
artifact after the fact.

Three detectors over a :class:`~langstream_tpu.providers.jax_local.engine.DecodeEngine`'s
public counters (read-only — the watchdog NEVER touches the data plane):

- **decode degradation** — per-poll decode latency vs a learned EWMA
  baseline, normalized per *accepted token* (the engine's
  ``decode_token_steps`` counter), not per scan step: with speculative
  decoding a step legitimately takes longer but yields 1..k+1 tokens,
  so a per-step baseline would read "enabling --spec-decode" as a
  degradation. The baseline only absorbs healthy samples, so a
  persistent 4× regression (thermal throttling, a neighbour hogging the
  chip, a pathological batch shape) trips instead of normalizing.
- **no progress** — work is waiting (queued/pending requests or active
  slots) but NO dispatch (decode chunk or prefill) completes for
  ``no_progress_s``: a hung dispatch, a deadlocked engine thread, a
  dead device tunnel. The default window is generous (120 s) because a
  first-seen jit variant legitimately blocks the engine thread for the
  whole compile — engines serving big models should precompile, and
  deployments that do can lower the window.
- **KV-pool livelock** (paged layout) — admissions are pending, the
  block pool is effectively exhausted, and no prefill lands for
  ``livelock_s``: every block is referenced by running work and nothing
  is releasing (PR 3's pool-pressure failure mode).

A trip flushes the flight recorder, writes a structured
``watchdog_trip`` flight event, bumps the process-wide
``watchdog_trips_total`` counter (exposed through ``engines_snapshot``
on every /metrics surface), and — rate-limited — triggers an automatic
profiler capture (:mod:`langstream_tpu.runtime.profiling`) so the
evidence window covers the stall itself.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

from langstream_tpu.api.metrics import Counter
from langstream_tpu.runtime import flight

logger = logging.getLogger(__name__)

# process-wide trip counter: every live watchdog counts into one series
# (same aggregation shape as the engine gauges)
TRIPS = Counter("watchdog_trips_total")


def trips_total() -> int:
    return TRIPS.value()


class EngineWatchdog:
    """Polls one engine; trip detection is in :meth:`check` so tests can
    drive it with injected clocks (no thread, no sleeps)."""

    def __init__(
        self,
        engine: Any,
        *,
        interval: float = 5.0,
        no_progress_s: float = 120.0,
        degrade_factor: float = 4.0,
        ewma_alpha: float = 0.2,
        min_baseline_chunks: int = 32,
        livelock_s: float = 30.0,
        livelock_free_frac: float = 0.05,
        trip_cooldown_s: float = 120.0,
        capture_profile: bool = True,
        capture_min_interval_s: float = 600.0,
        capture_seconds: float = 3.0,
        profile_dir: Optional[str] = None,
        escalate_trips: int = 3,
        escalate_window_s: float = 600.0,
    ) -> None:
        self.engine = engine
        self.interval = interval
        self.no_progress_s = no_progress_s
        self.degrade_factor = degrade_factor
        self.ewma_alpha = ewma_alpha
        self.min_baseline_chunks = min_baseline_chunks
        self.livelock_s = livelock_s
        self.livelock_free_frac = livelock_free_frac
        self.trip_cooldown_s = trip_cooldown_s
        self.capture_profile = capture_profile
        self.capture_min_interval_s = capture_min_interval_s
        self.capture_seconds = capture_seconds
        self.profile_dir = profile_dir
        # detector state is confined to the watchdog thread (tests
        # drive check() synchronously with no thread running — same
        # single-writer discipline)
        self.trips = 0  # owned-by: _loop
        self.baseline_step_s: Optional[float] = None  # owned-by: _loop
        self._baseline_chunks = 0  # owned-by: _loop
        # (ts, decode_chunks, decode_token_steps, decode_time,
        # prefill_calls) — token_steps is the per-accepted-token
        # normalizer (== decode_steps for a non-speculative engine)
        self._last: Optional[Tuple[float, int, float, float, int]] = None  # owned-by: _loop
        self._stall_anchor: Optional[float] = None  # owned-by: _loop
        self._livelock_anchor: Optional[float] = None  # owned-by: _loop
        self._last_trip: Dict[str, float] = {}  # owned-by: _loop
        self._last_capture: Optional[float] = None  # owned-by: _loop
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # escalation (the supervisor's second detection signal):
        # `escalate_trips` trips within `escalate_window_s` means the
        # engine is not healing on its own — hand it to `on_escalate`
        # (wired by EngineSupervisor to a snapshot/rebuild/resume
        # restart). Evidence-only behavior (flush, profile, counter) is
        # unchanged; with no callback the escalation is a no-op, and the
        # existing LANGSTREAM_WATCHDOG / --no-watchdog opt-out still
        # disables everything. Escalation fires ONCE per window.
        self.escalate_trips = max(1, int(escalate_trips))
        self.escalate_window_s = float(escalate_window_s)
        self.on_escalate: Optional[Any] = None
        self._trip_times: List[float] = []  # owned-by: _loop
        self._escalated_at: Optional[float] = None  # owned-by: _loop

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="engine-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            if self._thread is threading.current_thread():
                # called from our own loop (supervisor escalation tears
                # the old watchdog down from inside on_escalate): the
                # stop flag ends the loop right after this check returns
                self._thread = None
                return
            self._thread.join(timeout=self.interval + 5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if getattr(self.engine, "_crashed", None) is not None:
                # crash evidence is already flushed by the engine loop
                return
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog must never
                logger.exception("watchdog check failed")  # take anything down

    # ------------------------------------------------------------------ #
    # detection
    # ------------------------------------------------------------------ #
    def _work_waiting(self) -> bool:
        engine = self.engine
        if getattr(engine, "_pending", None):
            return True
        queue = getattr(engine, "_queue", None)
        if queue is not None and not queue.empty():
            return True
        return any(slot.active for slot in getattr(engine, "slots", []))

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """One detection pass; returns the trip reason (also after a
        cooldown-suppressed repeat) or None when healthy."""
        now = time.monotonic() if now is None else now
        stats = self.engine.stats
        chunks = stats["decode_chunks"]
        # per-ACCEPTED-TOKEN latency normalizer: a speculative step
        # yields 1..k+1 tokens, so dividing by scan steps would let
        # enabling spec-decode trip a false "degraded" (and, learned
        # spec-first, mask a real one). Engines predating the counter
        # fall back to raw steps (identical for non-speculative decode).
        steps = float(
            stats.get("decode_token_steps") or stats["decode_steps"]
        )
        decode_time = stats["decode_time"]
        prefills = stats["prefill_calls"] + stats["warm_prefill_calls"]
        reason: Optional[str] = None
        details: Dict[str, Any] = {}

        last = self._last
        # ANY completed dispatch is progress — a prefill-heavy backlog
        # (or a first-request jit compile finishing) must not read as a
        # stall just because no decode chunk landed
        progressed = last is not None and (
            chunks > last[1] or prefills > last[4]
        )
        if progressed:
            self._stall_anchor = None
            delta_steps = steps - last[2]
            if delta_steps > 0:
                step_s = max(0.0, decode_time - last[3]) / delta_steps
                if (
                    self.baseline_step_s is not None
                    and self._baseline_chunks >= self.min_baseline_chunks
                    and step_s
                    > self.degrade_factor * self.baseline_step_s
                ):
                    reason = "decode_degraded"
                    details = {
                        "step_ms": round(step_s * 1e3, 3),
                        "baseline_ms": round(
                            self.baseline_step_s * 1e3, 3
                        ),
                        "factor": round(step_s / self.baseline_step_s, 2),
                    }
                    # degraded samples must not poison the baseline
                else:
                    alpha = self.ewma_alpha
                    self.baseline_step_s = (
                        step_s if self.baseline_step_s is None
                        else (1 - alpha) * self.baseline_step_s
                        + alpha * step_s
                    )
                    self._baseline_chunks += chunks - last[1]
        elif self._work_waiting():
            if self._stall_anchor is None:
                self._stall_anchor = now
            elif now - self._stall_anchor >= self.no_progress_s:
                reason = "no_progress"
                details = {
                    "stalled_s": round(now - self._stall_anchor, 1),
                    "queue_depth": len(
                        getattr(self.engine, "_pending", []) or []
                    ),
                    "active_slots": sum(
                        1 for slot in getattr(self.engine, "slots", [])
                        if slot.active
                    ),
                }
        else:
            self._stall_anchor = None

        if reason is None:
            reason, details = self._check_livelock(now, prefills, last)

        self._last = (now, chunks, steps, decode_time, prefills)
        if reason is not None:
            self._trip(reason, details, now)
        return reason

    def _check_livelock(
        self,
        now: float,
        prefills: int,
        last: Optional[Tuple[float, int, int, float, int]],
    ) -> Tuple[Optional[str], Dict[str, Any]]:
        """Paged pool livelock: pending admissions, a near-exhausted
        pool, and no prefill landing across ``livelock_s`` — decode may
        still be making progress, which is exactly why the no-progress
        detector can't see this state."""
        engine = self.engine
        manager = getattr(engine, "kv_manager", None)
        if manager is None or not getattr(engine, "_pending", None):
            self._livelock_anchor = None
            return None, {}
        total = max(1, getattr(engine, "num_blocks", 1))
        free_frac = (total - manager.blocks_in_use) / total
        admitted = last is not None and prefills > last[4]
        if admitted or free_frac > self.livelock_free_frac:
            self._livelock_anchor = None
            return None, {}
        if self._livelock_anchor is None:
            self._livelock_anchor = now
            return None, {}
        if now - self._livelock_anchor < self.livelock_s:
            return None, {}
        return "kv_pool_livelock", {
            "stalled_s": round(now - self._livelock_anchor, 1),
            "queue_depth": len(engine._pending),
            "kv_blocks_in_use": manager.blocks_in_use,
            "kv_blocks_total": total,
        }

    # ------------------------------------------------------------------ #
    # trip
    # ------------------------------------------------------------------ #
    def _trip(
        self, reason: str, details: Dict[str, Any], now: float
    ) -> None:
        previous = self._last_trip.get(reason)
        if previous is not None and now - previous < self.trip_cooldown_s:
            return  # the stall is already reported; don't spam the ring
        self._last_trip[reason] = now
        self.trips += 1
        TRIPS.count()
        logger.warning("watchdog trip: %s %s", reason, details)
        # the flight artifact is the trip's on-disk evidence — flush the
        # ring NOW so the samples leading up to the stall survive even
        # if the process is killed next
        flight.record("watchdog_trip", reason=reason, **details)
        flight.flush()
        if self.capture_profile and (
            self._last_capture is None
            or now - self._last_capture >= self.capture_min_interval_s
        ):
            self._last_capture = now
            thread = threading.Thread(
                target=self._capture, name="watchdog-capture", daemon=True
            )
            thread.start()
        # escalation LAST: the trip's flight evidence is flushed above,
        # so a synchronous supervisor restart (which tears this watchdog
        # down from inside the callback) can't lose it
        self._note_escalation(reason, now)

    def _note_escalation(self, reason: str, now: float) -> None:
        self._trip_times.append(now)
        cutoff = now - self.escalate_window_s
        self._trip_times = [t for t in self._trip_times if t >= cutoff]
        if len(self._trip_times) < self.escalate_trips:
            return
        if (
            self._escalated_at is not None
            and now - self._escalated_at < self.escalate_window_s
        ):
            return  # one escalation per window — the restart is underway
        self._escalated_at = now
        flight.record(
            "watchdog_escalation",
            reason=reason,
            trips=len(self._trip_times),
            window_s=self.escalate_window_s,
        )
        flight.flush()
        callback = self.on_escalate
        if callback is None:
            return
        logger.error(
            "watchdog: %d trips within %.0fs — escalating (%s)",
            len(self._trip_times), self.escalate_window_s, reason,
        )
        try:
            callback(f"watchdog_escalation:{reason}")
        except Exception:  # noqa: BLE001 — escalation failing must not
            logger.exception("watchdog escalation failed")  # kill the loop

    def _capture(self) -> None:
        from langstream_tpu.runtime import profiling

        try:
            path = profiling.capture(
                self.capture_seconds, base_dir=self.profile_dir
            )
            logger.warning("watchdog: profiler capture -> %s", path)
            flight.record("watchdog_capture", path=path)
            flight.flush()
        except profiling.ProfileBusyError:
            pass  # an operator-triggered capture is already running
        except Exception:  # noqa: BLE001
            logger.exception("watchdog: profiler capture failed")
