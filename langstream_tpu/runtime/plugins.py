"""Agent plugin packages — the NAR-archive equivalent.

Reference: ``langstream-runtime/langstream-runtime-impl/src/main/java/ai/
langstream/runtime/agent/nar/NarFileHandler.java:44`` — agent bundles
shipped as archives, each loaded in its own classloader so two bundles'
internal classes never collide, with agent types discovered from the
bundle's metadata.

Python re-design: a plugin is a directory (or ``.zip``) containing

.. code-block:: yaml

    # plugin.yaml
    name: my-agents
    agents:
      my-source: "agents_module.MySource"     # module path inside python/
      my-mapper: "agents_module.MyMapper"

with the implementation under ``python/``. Isolation comes from module
namespacing: each plugin's code is imported under the synthetic package
``_ls_plugins.<name>`` whose ``__path__`` is the plugin's own ``python``
dir — so two plugins may both ship a ``util.py`` (or even the same
module names) without clashing, the moral equivalent of the reference's
per-NAR classloader. Agent types register lazily: the plugin module is
imported on first instantiation, not at scan time.
"""

from __future__ import annotations

import importlib
import importlib.util
import logging
import os
import sys
import types
import zipfile
from typing import Dict, List, Optional

import yaml

from langstream_tpu.runtime.registry import register_agent

logger = logging.getLogger(__name__)

_NAMESPACE = "_ls_plugins"
_loaded_plugins: Dict[str, str] = {}  # name -> source path


def _ensure_namespace_package() -> types.ModuleType:
    package = sys.modules.get(_NAMESPACE)
    if package is None:
        package = types.ModuleType(_NAMESPACE)
        package.__path__ = []  # type: ignore[attr-defined]
        sys.modules[_NAMESPACE] = package
    return package


def _plugin_package(name: str, python_dir: str) -> str:
    """Create (or refresh) the synthetic package for one plugin."""
    _ensure_namespace_package()
    qualified = f"{_NAMESPACE}.{name}"
    package = types.ModuleType(qualified)
    package.__path__ = [python_dir]  # type: ignore[attr-defined]
    package.__package__ = qualified
    sys.modules[qualified] = package
    # drop stale submodules of a previously-loaded version
    for module_name in list(sys.modules):
        if module_name.startswith(qualified + "."):
            del sys.modules[module_name]
    return qualified


def load_plugin(path: str) -> List[str]:
    """Load one plugin directory or ``.zip``; returns the agent types it
    registered."""
    import tempfile

    if path.endswith(".zip") and os.path.isfile(path):
        target = tempfile.mkdtemp(prefix="ls-plugin-")
        with zipfile.ZipFile(path) as archive:
            for member in archive.namelist():
                real = os.path.realpath(os.path.join(target, member))
                if not real.startswith(os.path.realpath(target) + os.sep):
                    raise ValueError(f"plugin member escapes archive: {member}")
            archive.extractall(target)
        path = target
    manifest_path = os.path.join(path, "plugin.yaml")
    if not os.path.isfile(manifest_path):
        raise ValueError(f"no plugin.yaml in {path}")
    with open(manifest_path, "r", encoding="utf-8") as handle:
        manifest = yaml.safe_load(handle) or {}
    name = manifest.get("name") or os.path.basename(path.rstrip("/"))
    name = name.replace("-", "_")
    agents = manifest.get("agents") or {}
    if not agents:
        raise ValueError(f"plugin {name!r} declares no agents")
    python_dir = os.path.join(path, "python")
    if not os.path.isdir(python_dir):
        python_dir = path
    qualified = _plugin_package(name, python_dir)

    registered: List[str] = []
    for agent_type, class_ref in agents.items():
        module_name, _, class_name = str(class_ref).replace(":", ".").rpartition(".")
        if not module_name:
            raise ValueError(
                f"plugin agent {agent_type!r}: class reference must be "
                f"'module.Class', got {class_ref!r}"
            )

        def factory(
            module_name: str = module_name, class_name: str = class_name
        ):
            module = importlib.import_module(f"{qualified}.{module_name}")
            return getattr(module, class_name)()

        register_agent(agent_type, factory)
        registered.append(agent_type)
    _loaded_plugins[name] = path
    logger.info("plugin %s: registered %s", name, registered)
    return registered


def load_plugins(directory: str) -> Dict[str, List[str]]:
    """Scan a plugins directory (each entry a plugin dir or .zip).
    The runner calls this with ``LANGSTREAM_PLUGINS_DIR`` when set."""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(directory):
        return out
    for entry in sorted(os.listdir(directory)):
        path = os.path.join(directory, entry)
        if entry.endswith(".zip") or (
            os.path.isdir(path)
            and os.path.isfile(os.path.join(path, "plugin.yaml"))
        ):
            try:
                out[entry] = load_plugin(path)
            except Exception:  # noqa: BLE001 — one bad plugin can't kill boot
                logger.exception("failed to load plugin %s", path)
    return out


def loaded_plugins() -> Dict[str, str]:
    return dict(_loaded_plugins)
