"""The data-plane runtime: the agent runner hot loop, batching, composition.

Equivalent of the reference's ``langstream-runtime`` module — see
``langstream-runtime/langstream-runtime-impl/src/main/java/ai/langstream/runtime/agent/AgentRunner.java``
for the loop being re-architected here, asyncio-first with XLA-aware
batch coalescing.
"""
