"""Request journey ledger (ISSUE 20): cross-replica latency attribution.

A request's life spans replicas — gateway route → prefill pool → KV
handoff over the topic fabric → decode pool — but traces dump per pod
and flight rings per process. The journey ledger closes the gap: every
hop stamps monotonic stage events keyed by the request's
``langstream-trace-id``, emitted as ``journey`` flight records on each
replica, and this module joins fleet-wide flight artifacts back into
per-request waterfalls, per-stage percentiles, and SLO blame.

Event schema — one ``journey`` flight record per finished (or handed
off) leg::

    {"ts": <epoch s>, "kind": "journey", "trace_id": ...,
     "session_id": ..., "replica": ..., "finish_reason": ...,
     "tokens": N, "admit_class": "cold"|"hbm-hit"|"host-promote"|
     "handoff-import", "first_token": <wall s or absent>,
     "stages": [{"stage": <name>, "start": <wall s>, "end": <wall s>,
                 ...attrs}]}

Stage names (``STAGES``): ``route`` (gateway/fleet router decision,
emitted by the routing process), ``queue``, ``admit`` (zero-width,
carries the admission class), ``prefill``, ``handoff_export`` /
``handoff_transit`` / ``handoff_import`` (the disaggregation hop —
transit is computable on the decode side because the export timestamp
rides the chunk-0 manifest, ``fleet/handoff.py``), ``decode``,
``finish``. Within one leg the boundaries chain (each stage starts
where the previous ended), so the stages tile the leg's wall clock by
construction; across legs the export stamp chains the prefill leg's
end to the decode leg's transit start.

Blame semantics: a TTFT violation is attributed to the stage with the
largest overlap of the window [journey start, first token]; a TPOT
violation to the largest overlap of [first token, journey end]. An
injected slow handoff therefore lands on ``handoff_transit``, a pool
backlog on ``queue``, a cold monolithic prefill on ``prefill``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from langstream_tpu.api.metrics import Histogram

# canonical stage set — also the tie-break order for blame
STAGES: Tuple[str, ...] = (
    "route", "queue", "admit", "prefill", "handoff_export",
    "handoff_transit", "handoff_import", "decode", "finish",
)

# stages every completed single-leg journey is expected to carry; a
# torn journey (replica died mid-request) reports what is missing
CORE_STAGES: Tuple[str, ...] = (
    "queue", "admit", "prefill", "decode", "finish",
)

ADMIT_CLASSES: Tuple[str, ...] = (
    "cold", "hbm-hit", "host-promote", "handoff-import",
)

# per-stage latency histograms: one family per stage so every /metrics
# surface (runner pod, OpenAI server, gateway) exports the same
# ``jax_engine_journey_<stage>_seconds`` buckets the ledger's offline
# percentiles are computed from. Buckets span the engine's sub-ms admit
# up through a sim-clock (or badly backlogged) multi-second queue.
_STAGE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)
STAGE_SECONDS: Dict[str, Histogram] = {
    name: Histogram(
        f"jax_engine_journey_{name}_seconds", buckets=_STAGE_BUCKETS
    )
    for name in STAGES
}


def stage_histograms() -> Dict[str, Dict[str, float]]:
    """Snapshot view for ``engines_histograms()`` — and through it,
    every Prometheus surface in the process."""
    return {h.name: h.snapshot() for h in STAGE_SECONDS.values()}


def observe_stages(stages: Iterable[Mapping[str, Any]]) -> None:
    for stage in stages:
        histogram = STAGE_SECONDS.get(stage.get("stage"))
        if histogram is not None:
            histogram.observe(
                max(0.0, float(stage["end"]) - float(stage["start"]))
            )


class StageBuilder:
    """Accumulates one leg's stage events with monotonic boundaries:
    each stage's start is clamped to the previous stage's end and its
    end to its own start, so the emitted leg can never contain a
    negative or overlapping stage — the tiling invariant holds by
    construction, whatever clock skew the raw anchors carried."""

    def __init__(self) -> None:
        self.stages: List[Dict[str, Any]] = []
        self._cursor: Optional[float] = None

    def add(
        self, stage: str, start: float, end: float, **attrs: Any
    ) -> "StageBuilder":
        start = float(start)
        end = float(end)
        if self._cursor is not None:
            start = max(start, self._cursor)
        end = max(end, start)
        self._cursor = end
        event = {"stage": stage, "start": start, "end": end}
        event.update(attrs)
        self.stages.append(event)
        return self


def blame_stage(
    stages: Sequence[Mapping[str, Any]],
    first_token: Optional[float],
    kind: str,
) -> Optional[str]:
    """The dominant stage for one SLO violation: largest overlap with
    the violated window — TTFT looks before the first token, TPOT
    after. Ties break toward the canonical stage order. ``finish`` is
    bookkeeping, never a verdict."""
    if not stages:
        return None
    if first_token is None:
        lo, hi = float("-inf"), float("inf")
    elif kind == "ttft":
        lo, hi = float("-inf"), float(first_token)
    else:
        lo, hi = float(first_token), float("inf")
    best: Optional[str] = None
    best_overlap = 0.0
    for stage in stages:
        name = stage.get("stage")
        if name == "finish":
            continue
        overlap = min(float(stage["end"]), hi) - max(
            float(stage["start"]), lo
        )
        rank = STAGES.index(name) if name in STAGES else len(STAGES)
        if overlap > best_overlap or (
            overlap == best_overlap
            and best is not None
            and overlap > 0.0
            and rank < (
                STAGES.index(best) if best in STAGES else len(STAGES)
            )
        ):
            best = name
            best_overlap = overlap
    return best if best_overlap > 0.0 else None


# boundary jitter tolerance: journey anchors are wall-clock floats
# rounded independently per record; anything under a microsecond is a
# serialization artifact, not a scheduling overlap
EPS = 2e-6


class Journey:
    """One request's merged view across every replica it crossed: all
    ``journey`` records sharing a trace id, their stages flattened and
    time-sorted."""

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.records: List[Dict[str, Any]] = []

    def add(self, record: Dict[str, Any]) -> None:
        self.records.append(record)

    # -------------------------------------------------------------- #
    # merged stage view
    # -------------------------------------------------------------- #
    @property
    def stages(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for record in self.records:
            replica = record.get("replica") or ""
            for stage in record.get("stages") or ():
                event = dict(stage)
                event.setdefault("replica", replica)
                out.append(event)
        out.sort(key=lambda s: (float(s["start"]), float(s["end"])))
        return out

    @property
    def start(self) -> Optional[float]:
        stages = self.stages
        return float(stages[0]["start"]) if stages else None

    @property
    def end(self) -> Optional[float]:
        stages = self.stages
        return max(float(s["end"]) for s in stages) if stages else None

    @property
    def e2e_s(self) -> float:
        stages = self.stages
        if not stages:
            return 0.0
        return max(float(s["end"]) for s in stages) - float(
            stages[0]["start"]
        )

    @property
    def replicas(self) -> List[str]:
        """Replicas in order of first appearance on the timeline."""
        seen: List[str] = []
        for stage in self.stages:
            replica = stage.get("replica") or ""
            if replica and replica not in seen:
                seen.append(replica)
        return seen

    @property
    def first_token(self) -> Optional[float]:
        candidates = [
            float(r["first_token"]) for r in self.records
            if r.get("first_token") is not None
        ]
        return min(candidates) if candidates else None

    @property
    def tokens(self) -> int:
        return max(
            (int(r.get("tokens") or 0) for r in self.records), default=0
        )

    @property
    def admit_classes(self) -> List[str]:
        return [
            str(r["admit_class"]) for r in self.records
            if r.get("admit_class")
        ]

    @property
    def finished(self) -> bool:
        return any(
            s.get("stage") == "finish" for s in self.stages
        )

    def missing_stages(self) -> List[str]:
        present = {s.get("stage") for s in self.stages}
        return [s for s in CORE_STAGES if s not in present]

    # -------------------------------------------------------------- #
    # the tiling invariant
    # -------------------------------------------------------------- #
    def coverage(self) -> float:
        """Fraction of the journey's end-to-end wall covered by the
        union of its stage intervals (1.0 = the stages tile the whole
        request; a gap means somebody's time went unattributed)."""
        stages = self.stages
        if not stages:
            return 0.0
        e2e = self.e2e_s
        if e2e <= 0.0:
            return 1.0
        covered = 0.0
        cursor = float(stages[0]["start"])
        for stage in stages:
            start = max(float(stage["start"]), cursor)
            end = float(stage["end"])
            if end > start:
                covered += end - start
                cursor = end
        return covered / e2e

    def overlaps(self) -> List[Tuple[str, str, float]]:
        """Pairs of stages whose intervals overlap by more than EPS —
        double-billed wall clock the blame table would misattribute."""
        out: List[Tuple[str, str, float]] = []
        stages = self.stages
        for i, stage in enumerate(stages):
            for other in stages[i + 1:]:
                if float(other["start"]) >= float(stage["end"]) - EPS:
                    break
                amount = min(
                    float(stage["end"]), float(other["end"])
                ) - float(other["start"])
                if amount > EPS:
                    out.append(
                        (stage["stage"], other["stage"], amount)
                    )
        return out

    def negatives(self) -> List[str]:
        return [
            s["stage"] for s in self.stages
            if float(s["end"]) < float(s["start"]) - EPS
        ]

    # -------------------------------------------------------------- #
    # latency + blame
    # -------------------------------------------------------------- #
    def ttft_s(self) -> Optional[float]:
        first = self.first_token
        start = self.start
        if first is None or start is None:
            return None
        return max(0.0, first - start)

    def tpot_s(self) -> Optional[float]:
        """Mean inter-token gap after the first token, journey-wide —
        a slow handoff between the prefill leg's first token and the
        decode leg's second shows up here, exactly where the client
        feels it."""
        first = self.first_token
        end = self.end
        if first is None or end is None or self.tokens <= 1:
            return None
        decode_end = max(
            (
                float(s["end"]) for s in self.stages
                if s.get("stage") == "decode"
            ),
            default=end,
        )
        return max(0.0, decode_end - first) / (self.tokens - 1)

    def stage_totals(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for stage in self.stages:
            name = stage.get("stage")
            totals[name] = totals.get(name, 0.0) + max(
                0.0, float(stage["end"]) - float(stage["start"])
            )
        return totals

    def blame(self, kind: str) -> Optional[str]:
        return blame_stage(self.stages, self.first_token, kind)


def _percentile(values: Sequence[float], fraction: float) -> float:
    ordered = sorted(values)
    if not ordered:
        return 0.0
    index = min(len(ordered) - 1, int(round(fraction * (len(ordered) - 1))))
    return ordered[index]


class JourneyLedger:
    """Joins fleet-wide flight artifacts by trace id.

    Thread-safe: the CLI uses it single-threaded, but a live dashboard
    (``top``-style pollers) may feed artifacts from a reader thread
    while another renders.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # trace_id -> Journey  # guarded-by: _lock
        self._journeys: Dict[str, Journey] = {}
        self.artifacts = 0  # guarded-by: _lock
        self.replicas: Dict[str, str] = {}  # guarded-by: _lock

    def add_artifact(self, path: str) -> int:
        """Read one flight JSONL artifact; its ``meta`` record labels
        every journey record with the emitting replica + fleet role
        (older artifacts without the identity stamp fall back to the
        file name). Returns the number of journey records absorbed."""
        from langstream_tpu.runtime import flight

        records = flight.read_artifact(path)
        replica = ""
        role = ""
        for record in records:
            if record.get("kind") == "meta":
                replica = str(record.get("replica") or replica)
                role = str(record.get("fleet_role") or role)
        if not replica:
            replica = os.path.splitext(os.path.basename(path))[0]
        return self.add_records(records, replica=replica, role=role)

    def add_records(
        self,
        records: Iterable[Mapping[str, Any]],
        *,
        replica: str = "",
        role: str = "",
    ) -> int:
        count = 0
        with self._lock:
            if replica:
                self.replicas[replica] = role
            self.artifacts += 1
            for record in records:
                if record.get("kind") != "journey":
                    continue
                trace_id = str(record.get("trace_id") or "")
                if not trace_id:
                    continue
                entry = dict(record)
                entry.setdefault("replica", replica)
                entry.setdefault("fleet_role", role)
                journey = self._journeys.get(trace_id)
                if journey is None:
                    journey = self._journeys[trace_id] = Journey(trace_id)
                journey.add(entry)
                count += 1
        return count

    def journeys(self) -> List[Journey]:
        with self._lock:
            return sorted(
                self._journeys.values(),
                key=lambda j: j.start if j.start is not None else 0.0,
            )

    def get(self, trace_id: str) -> Optional[Journey]:
        with self._lock:
            return self._journeys.get(trace_id)

    # -------------------------------------------------------------- #
    # aggregates
    # -------------------------------------------------------------- #
    def stage_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-stage duration percentiles across every journey."""
        samples: Dict[str, List[float]] = {}
        for journey in self.journeys():
            for stage in journey.stages:
                samples.setdefault(stage["stage"], []).append(
                    max(
                        0.0,
                        float(stage["end"]) - float(stage["start"]),
                    )
                )
        return {
            name: {
                "count": float(len(values)),
                "p50_s": _percentile(values, 0.50),
                "p95_s": _percentile(values, 0.95),
                "total_s": sum(values),
            }
            for name, values in samples.items()
        }

    def blame_table(
        self,
        *,
        slo_ttft_s: Optional[float] = None,
        slo_tpot_s: Optional[float] = None,
    ) -> Dict[str, Dict[str, int]]:
        """For each TTFT/TPOT-violating journey, the dominant stage —
        aggregated into the blame table the CLI renders."""
        table: Dict[str, Dict[str, int]] = {"ttft": {}, "tpot": {}}
        for journey in self.journeys():
            ttft = journey.ttft_s()
            if slo_ttft_s and ttft is not None and ttft > slo_ttft_s:
                stage = journey.blame("ttft")
                if stage:
                    table["ttft"][stage] = (
                        table["ttft"].get(stage, 0) + 1
                    )
            tpot = journey.tpot_s()
            if slo_tpot_s and tpot is not None and tpot > slo_tpot_s:
                stage = journey.blame("tpot")
                if stage:
                    table["tpot"][stage] = (
                        table["tpot"].get(stage, 0) + 1
                    )
        return table


# ------------------------------------------------------------------ #
# CLI body (``langstream-tpu journey``) + the ab_analyze digest
# ------------------------------------------------------------------ #
def collect_flight_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(
                os.path.join(path, name)
                for name in sorted(os.listdir(path))
                if name.startswith("flight_") and name.endswith(".jsonl")
            )
        elif os.path.isfile(path):
            out.append(path)
    return out


def waterfall_lines(journey: Journey) -> List[str]:
    """One journey rendered as an indented waterfall: each stage's
    offset from journey start, duration, replica, and attributes."""
    start = journey.start or 0.0
    replicas = ">".join(journey.replicas) or "?"
    classes = ",".join(journey.admit_classes)
    header = (
        f"{journey.trace_id}  e2e {journey.e2e_s:.3f}s"
        f"  tokens={journey.tokens}  replicas={replicas}"
    )
    if classes:
        header += f"  admit={classes}"
    missing = journey.missing_stages()
    if missing:
        header += f"  MISSING={','.join(missing)}"
    lines = [header]
    for stage in journey.stages:
        duration = max(
            0.0, float(stage["end"]) - float(stage["start"])
        )
        attrs = {
            k: v for k, v in stage.items()
            if k not in ("stage", "start", "end", "replica")
        }
        extra = (
            "  " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            if attrs else ""
        )
        lines.append(
            f"  {stage['stage']:<16} +{float(stage['start']) - start:8.3f}s"
            f"  {duration:8.3f}s  {stage.get('replica', '')}{extra}"
        )
    return lines


def run_journey(
    paths: Sequence[str],
    *,
    trace_id: Optional[str] = None,
    slo_ttft_ms: float = 0.0,
    slo_tpot_ms: float = 0.0,
    as_json: bool = False,
    waterfalls: int = 3,
) -> List[str]:
    """The CLI body behind ``langstream-tpu journey``: join flight
    artifacts, render waterfalls / per-stage percentiles / SLO blame.
    Returns the lines to print."""
    files = collect_flight_files(paths)
    if not files:
        raise SystemExit(f"no flight artifacts under {list(paths)}")
    ledger = JourneyLedger()
    for path in files:
        ledger.add_artifact(path)
    journeys = ledger.journeys()
    slo_ttft_s = slo_ttft_ms / 1e3 if slo_ttft_ms else None
    slo_tpot_s = slo_tpot_ms / 1e3 if slo_tpot_ms else None
    if trace_id is not None:
        journey = ledger.get(trace_id)
        if journey is None:
            raise SystemExit(
                f"trace id {trace_id!r} not found in {len(files)} "
                f"artifact(s) ({len(journeys)} journeys)"
            )
        journeys = [journey]
    if as_json:
        doc = {
            "artifacts": len(files),
            "journeys": [
                {
                    "trace_id": j.trace_id,
                    "e2e_s": round(j.e2e_s, 6),
                    "ttft_s": j.ttft_s(),
                    "tpot_s": j.tpot_s(),
                    "tokens": j.tokens,
                    "replicas": j.replicas,
                    "admit_classes": j.admit_classes,
                    "coverage": round(j.coverage(), 4),
                    "finished": j.finished,
                    "missing_stages": j.missing_stages(),
                    "stages": j.stages,
                }
                for j in journeys
            ],
            "stage_stats": ledger.stage_stats(),
            "blame": ledger.blame_table(
                slo_ttft_s=slo_ttft_s, slo_tpot_s=slo_tpot_s
            ),
        }
        return [json.dumps(doc, indent=2)]
    lines = [
        f"{len(journeys)} journey(s) from {len(files)} flight "
        f"artifact(s) across "
        f"{len([r for r in ledger.replicas if r])} replica(s)"
    ]
    if trace_id is not None:
        lines.extend(waterfall_lines(journeys[0]))
    else:
        stats = ledger.stage_stats()
        if stats:
            lines.append("")
            lines.append(
                f"  {'stage':<16} {'count':>6} {'p50':>9} {'p95':>9} "
                f"{'total':>9}"
            )
            for name in STAGES:
                if name not in stats:
                    continue
                entry = stats[name]
                lines.append(
                    f"  {name:<16} {int(entry['count']):>6}"
                    f" {entry['p50_s']:>8.3f}s {entry['p95_s']:>8.3f}s"
                    f" {entry['total_s']:>8.3f}s"
                )
        torn = [j for j in journeys if j.missing_stages()]
        if torn:
            lines.append("")
            lines.append(
                f"  {len(torn)} torn journey(s) "
                "(replica died mid-request; partial stages kept):"
            )
            for journey in torn[:waterfalls]:
                lines.append(
                    f"    {journey.trace_id}  missing="
                    f"{','.join(journey.missing_stages())}"
                )
        # the slowest journeys, rendered as waterfalls
        for journey in sorted(
            journeys, key=lambda j: -j.e2e_s
        )[:max(0, waterfalls)]:
            lines.append("")
            lines.extend(waterfall_lines(journey))
    if slo_ttft_s or slo_tpot_s:
        blame = ledger.blame_table(
            slo_ttft_s=slo_ttft_s, slo_tpot_s=slo_tpot_s
        )
        lines.append("")
        lines.append("SLO blame (violating requests by dominant stage):")
        for kind in ("ttft", "tpot"):
            for stage, count in sorted(
                blame[kind].items(), key=lambda kv: -kv[1]
            ):
                lines.append(f"  {kind}  {stage:<16} {count}")
        if not blame["ttft"] and not blame["tpot"]:
            lines.append("  no violations")
    return lines


def journey_digest(directory: str) -> Optional[List[str]]:
    """Compact per-stage digest over every flight artifact in a
    directory — the ``tools/ab_analyze.py`` hook. None when no journey
    records exist (pre-ledger artifacts)."""
    files = collect_flight_files([directory])
    if not files:
        return None
    ledger = JourneyLedger()
    total = sum(ledger.add_artifact(path) for path in files)
    if not total:
        return None
    stats = ledger.stage_stats()
    journeys = ledger.journeys()
    crossed = [j for j in journeys if len(j.replicas) > 1]
    lines = [
        f"  journeys: {len(journeys)} across "
        f"{len(ledger.replicas)} replica(s)"
        + (f", {len(crossed)} multi-replica" if crossed else "")
    ]
    for name in STAGES:
        if name not in stats:
            continue
        entry = stats[name]
        lines.append(
            f"    {name:<16} p50 {entry['p50_s'] * 1e3:7.1f} ms  "
            f"p95 {entry['p95_s'] * 1e3:7.1f} ms  "
            f"({int(entry['count'])})"
        )
    return lines
