"""Engine supervisor — crash → rebuild → resume, not crash → mass 500.

DeepServe (PAPERS.md, arxiv 2501.14417) treats fast failure detection
and instance recovery as first-class serving properties, and AIBrix
(arxiv 2504.03648) assumes runners fail routinely; this module is the
single-engine arm of that story. An :class:`EngineSupervisor` owns a
:class:`~langstream_tpu.providers.jax_local.engine.DecodeEngine`'s
lifecycle:

1. **Detect** — the engine's device thread dying (``engine.on_crash``)
   or a watchdog escalation (N trips inside a window →
   :meth:`request_restart`).
2. **Snapshot** — every live session's replay state via
   ``engine.drain_for_recovery()``: prompt ids + accepted generated
   tokens (with their logprobs), ``SamplingParams`` incl. the pinned
   seed, per-slot penalty history, budget consumed so far. Queued and
   still-prefilling requests snapshot untouched (no token ever reached
   their caller).
3. **Heal** — tear the engine down, rebuild via the factory closure
   (weights are reused in place; jit executables come back through the
   persistent XLA compile cache where shapes match), and re-admit every
   session as a warm replay prefill that fast-forwards through its own
   history. Sampling keys derive from ``(seed, position)`` and penalty
   counts replay position-exactly, so a seeded or greedy session's
   continuation is **bitwise identical** to the uncrashed oracle; the
   paged prefix cache makes the replay prefill cheap and the recomputed
   tokens are billed as ``tokens_wasted{crash_replay}``.

While rebuilding, the serving surfaces answer 503 + ``Retry-After``
(``EngineRebuildingError``), in-flight SSE streams pause and then
resume mid-generation (their futures/callbacks ride the replay
request), and recovery emits ``engine_restarts_total`` /
``sessions_resurrected_total`` / the ``engine_recovery_seconds``
histogram on every /metrics surface, ``engine_recovery`` flight events,
and an ``engine.recovery`` trace span.

A restart budget (``max_restarts`` within ``restart_window_s``) stops a
crash-looping engine from burning the host forever: past it the
supervisor fails the drained waiters once and goes ``failed``.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import weakref
from typing import Any, Callable, Deque, Dict, List, Optional

from langstream_tpu.api.metrics import Counter, Histogram
from langstream_tpu.runtime import flight
from langstream_tpu.runtime.tracing import get_tracer

logger = logging.getLogger(__name__)

# process-wide recovery series (same aggregation shape as the engine
# gauges / watchdog trips: every supervisor counts into one family,
# exposed through engines_snapshot on every /metrics surface)
ENGINE_RESTARTS = Counter("engine_restarts_total")
SESSIONS_RESURRECTED = Counter("sessions_resurrected_total")
RECOVERY_SECONDS = Histogram(
    "engine_recovery_seconds",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0, 300.0),
)

_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()


def supervisor_gauges() -> Dict[str, float]:
    """Recovery gauges for ``engines_snapshot``. Empty until the first
    supervisor exists so unsupervised processes export nothing new;
    once one does, the series exist from construction (0 included) —
    rate() alerts need the family BEFORE the first restart, and the
    degraded gauge matters precisely while zero engines are live."""
    # snapshot-tolerant WeakSet read: supervisors register from the
    # crash path (the dying engine thread) while scrape threads iterate
    from langstream_tpu.utils.threadsafe import stable_list

    supervisors = stable_list(_ACTIVE)
    if not supervisors and ENGINE_RESTARTS.value() == 0:
        return {}
    # degraded = actively rebuilding or terminally failed; a cleanly
    # stopped supervisor (process shutdown) is not an incident
    degraded = any(
        s.state in ("rebuilding", "failed") for s in supervisors
    )
    return {
        "engine_restarts_total": float(ENGINE_RESTARTS.value()),
        "sessions_resurrected_total": float(SESSIONS_RESURRECTED.value()),
        "engine_degraded": 1.0 if degraded else 0.0,
    }


def supervisor_histograms() -> Dict[str, Dict[str, float]]:
    snapshot = RECOVERY_SECONDS.snapshot()
    if not _ACTIVE and not snapshot.get("count"):
        return {}
    return {RECOVERY_SECONDS.name: snapshot}


class EngineSupervisor:
    """Owns one engine's lifecycle. ``factory`` builds a fresh, NOT yet
    started engine (capturing config + already-loaded weights, so a
    rebuild never reloads a checkpoint); ``watchdog_factory``
    (optional) builds an
    :class:`~langstream_tpu.runtime.watchdog.EngineWatchdog` for a
    given engine — the supervisor wires its ``on_escalate`` and owns
    its start/stop across rebuilds."""

    def __init__(
        self,
        factory: Callable[[], Any],
        *,
        max_restarts: int = 3,
        restart_window_s: float = 600.0,
        watchdog_factory: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self.factory = factory
        self.max_restarts = max(0, int(max_restarts))
        self.restart_window_s = float(restart_window_s)
        self.watchdog_factory = watchdog_factory
        # lifecycle state machine: transitions hold the lock; readers
        # (accepting(), heartbeats) take lock-free stale-tolerant
        # snapshots — blocking a 503-availability check behind a
        # multi-second rebuild held under the lock would freeze every
        # handler exactly when fast failure matters
        self.state = "serving"  # guarded-by: _lock (writes)
        self.restarts = 0  # guarded-by: _lock (writes)
        self.last_recovery_s: Optional[float] = None  # guarded-by: _lock (writes)
        self._restart_times: Deque[float] = collections.deque()  # guarded-by: _lock
        self._lock = threading.RLock()
        self.tracer = get_tracer("engine")
        # the engine generation pointer: swapped under the lock by the
        # heal arc; the serving-surface property reads it lock-free (a
        # stale engine is condemned and fails fast on submit)
        self._engine = factory()  # guarded-by: _lock (writes)
        self._engine.on_crash = self._make_crash_hook(self._engine)
        self.watchdog = self._build_watchdog(self._engine)  # guarded-by: _lock (writes)
        self._engine.start()
        if self.watchdog is not None:
            self.watchdog.start()
        _ACTIVE.add(self)

    # ------------------------------------------------------------------ #
    # serving-surface view
    # ------------------------------------------------------------------ #
    @property
    def engine(self) -> Any:
        return self._engine

    def accepting(self) -> bool:
        return self.state == "serving"

    def retry_after(self) -> float:
        """Seconds a 503'd caller should wait before retrying: the last
        observed rebuild time (a fresh supervisor guesses 2 s)."""
        return max(1.0, self.last_recovery_s or 2.0)

    def stop(self) -> None:
        """Clean shutdown (provider close): no resurrection."""
        with self._lock:
            self.state = "stopped"
            watchdog, self.watchdog = self.watchdog, None
            engine = self._engine
        # join the watchdog OUTSIDE the lock: its thread may itself be
        # blocked on the lock inside request_restart
        if watchdog is not None:
            watchdog.stop()
        engine.on_crash = None
        engine.stop()

    # ------------------------------------------------------------------ #
    # detect
    # ------------------------------------------------------------------ #
    def _make_crash_hook(self, engine: Any):
        def hook(error: BaseException) -> None:
            # runs on the dying engine thread, after the crash flag is
            # set and flight evidence flushed — the whole heal arc
            # executes here (the thread was about to exit anyway)
            self._restart(engine, error, f"engine_crash:{type(error).__name__}")

        return hook

    def request_restart(
        self, reason: str, engine: Optional[Any] = None
    ) -> None:
        """Escalation path (watchdog: N trips in a window): the engine
        is wedged or persistently degraded but its thread may still be
        alive — condemn it, give the thread a bounded chance to exit
        cleanly, then run the same snapshot → rebuild → resume arc.

        ``engine`` pins the escalation to the engine the caller was
        watching: a stale watchdog whose escalation lost a race against
        an organic crash+rebuild must NOT condemn the healthy
        replacement (identity-checked under the lock)."""
        with self._lock:
            if engine is None:
                engine = self._engine
            if engine is not self._engine or self.state != "serving":
                return
            # condemn BEFORE stopping: racing submits get the typed
            # rebuilding error (503), never a torn queue. on_crash stays
            # set so a late organic crash of this engine is ignored by
            # identity in _restart rather than failing waiters.
            engine._crashed = RuntimeError(f"supervisor restart: {reason}")
            engine._running = False
        engine._queue.put(None)  # wake an idle loop so the thread exits
        thread = engine._thread
        if thread is not None and thread is not threading.current_thread():
            # a degraded-but-alive thread exits within one iteration; a
            # truly wedged one times out (it is not emitting anyway) and
            # drain_for_recovery's slot neutralization fences it off
            thread.join(timeout=10.0)
        self._restart(engine, RuntimeError(reason), reason)

    # ------------------------------------------------------------------ #
    # heal
    # ------------------------------------------------------------------ #
    def _restart(
        self, engine: Any, error: BaseException, reason: str
    ) -> None:
        with self._lock:
            if engine is not self._engine or self.state in (
                "failed", "stopped",
            ):
                return  # stale hook (already superseded) or terminal
            self.state = "rebuilding"
            started = time.perf_counter()
            started_wall = time.time()
            now = time.monotonic()
            while (
                self._restart_times
                and now - self._restart_times[0] > self.restart_window_s
            ):
                self._restart_times.popleft()
            self._restart_times.append(now)
            over_budget = len(self._restart_times) > self.max_restarts
            requests = engine.drain_for_recovery()
            replayed = sum(1 for r in requests if r.replay_tokens)
            engine.retire()
            old_stats = engine.stats
            if self.watchdog is not None:
                self.watchdog.stop()
                self.watchdog = None
            flight.record(
                "engine_recovery",
                phase="begin",
                reason=reason,
                error=repr(error)[:256],
                sessions=len(requests),
                replayed=replayed,
                restart=len(self._restart_times),
            )
            flight.flush()
            if over_budget:
                self.state = "failed"
                # terminal: later submits must surface a plain 500, not
                # an endless retryable 503
                engine.on_crash = None
                logger.error(
                    "supervisor: %d restarts within %.0fs — giving up",
                    len(self._restart_times), self.restart_window_s,
                )
                flight.record(
                    "engine_recovery", phase="gave_up", reason=reason,
                    restarts=len(self._restart_times),
                )
                flight.flush()
                self._fail_requests(requests, RuntimeError(
                    f"engine crashed {len(self._restart_times)} times "
                    f"within {self.restart_window_s:.0f}s "
                    f"(max-restarts {self.max_restarts}); giving up"
                ))
                return
            logger.warning(
                "supervisor: rebuilding engine (%s; %d live sessions, "
                "%d with accepted tokens)",
                reason, len(requests), replayed,
            )
            try:
                # the WHOLE heal arc is covered: a failure anywhere in
                # rebuild / start / resubmit must fail the drained
                # waiters and land in a terminal state — an escaped
                # exception here would leave every caller hanging and
                # the supervisor 503ing forever from "rebuilding"
                rebuilt = self.factory()
                # metrics continuity: the replacement inherits the dead
                # engine's cumulative counters so no series resets
                # mid-incident
                rebuilt.absorb_stats(old_stats)
                rebuilt.on_crash = self._make_crash_hook(rebuilt)
                self._engine = rebuilt
                rebuilt.start()
                resurrected = 0
                for request in requests:
                    try:
                        rebuilt.submit(request)
                        resurrected += 1
                    except Exception:  # noqa: BLE001 — one bad resubmit
                        logger.exception(  # must not doom the rest
                            "supervisor: failed to resurrect a session"
                        )
                        self._fail_requests([request], RuntimeError(
                            "session could not be resurrected after an "
                            "engine rebuild"
                        ))
                try:
                    # a broken watchdog must not doom a healthy rebuilt
                    # engine that already carries resurrected sessions —
                    # serve unwatched rather than fail everything
                    self.watchdog = self._build_watchdog(rebuilt)
                    if self.watchdog is not None:
                        self.watchdog.start()
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "supervisor: watchdog rebuild failed; serving "
                        "without a watchdog"
                    )
                    self.watchdog = None
            except Exception as heal_error:  # noqa: BLE001
                self.state = "failed"
                engine.on_crash = None
                broken = self._engine
                if broken is not engine:
                    # a half-initialized replacement is already
                    # installed (start() raised): condemn it so later
                    # submits fail FAST (plain 500) instead of
                    # enqueueing into an engine whose thread never ran
                    broken.on_crash = None
                    if broken._crashed is None:
                        broken._crashed = RuntimeError(
                            "engine rebuild failed"
                        )
                    broken._running = False
                    broken.retire()
                logger.exception("supervisor: engine rebuild failed")
                flight.record(
                    "engine_recovery", phase="rebuild_failed",
                    error=repr(heal_error)[:256],
                )
                flight.flush()
                self._fail_requests(requests, RuntimeError(
                    "engine rebuild failed; see logs"
                ) if not isinstance(heal_error, RuntimeError)
                    else heal_error)
                return
            recovery_s = time.perf_counter() - started
            ENGINE_RESTARTS.count()
            SESSIONS_RESURRECTED.count(resurrected)
            RECOVERY_SECONDS.observe(recovery_s)
            self.restarts += 1
            self.last_recovery_s = recovery_s
            self.state = "serving"
        self.tracer.event(
            "engine.recovery",
            recovery_s,
            start_wall=started_wall,
            reason=reason,
            sessions=resurrected,
            replayed=replayed,
        )
        flight.record(
            "engine_recovery",
            phase="complete",
            reason=reason,
            sessions=resurrected,
            replayed=replayed,
            recovery_s=round(recovery_s, 4),
        )
        flight.flush()
        logger.warning(
            "supervisor: engine rebuilt in %.2fs, %d sessions resurrected",
            recovery_s, resurrected,
        )

    def _build_watchdog(self, engine: Any):
        if self.watchdog_factory is None:
            return None
        watchdog = self.watchdog_factory(engine)
        if watchdog is not None:
            # bind the escalation to THIS engine's generation (see
            # request_restart's identity check)
            watchdog.on_escalate = (
                lambda reason, _engine=engine:
                self.request_restart(reason, engine=_engine)
            )
        return watchdog

    @staticmethod
    def _fail_requests(requests: List[Any], error: BaseException) -> None:
        from langstream_tpu.providers.jax_local.engine import (
            fail_request_future,
        )

        for request in requests:
            fail_request_future(request, error)
