"""Agent code registry: agent-type → factory.

Equivalent of the reference's ServiceLoader-based registry
(``langstream-api/src/main/java/ai/langstream/api/runner/code/AgentCodeRegistry.java:32``):
the runner resolves the implementation of each execution-plan node by its
``agentType``. Python has no ServiceLoader; built-in agents register at
import time and applications can register custom agents programmatically or
via ``python`` agents (module:Class references resolved at load).
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, Optional

from langstream_tpu.api.agent import Agent

AgentFactory = Callable[[], Agent]

_AGENTS: Dict[str, AgentFactory] = {}


def register_agent(agent_type: str, factory: AgentFactory) -> None:
    _AGENTS[agent_type] = factory


def agent_types() -> list:
    return sorted(_AGENTS)


def create_agent(agent_type: str) -> Agent:
    """Instantiate the agent for ``agent_type``.

    ``python-processor`` / ``python-source`` / ``python-sink`` /
    ``python-service`` are resolved lazily at ``init`` time from their
    ``className`` config (reference analogue: the gRPC Python bridge,
    ``langstream-agent-grpc/.../PythonGrpcServer.java:31`` — here Python
    agents run in-process, no bridge needed).
    """
    _ensure_builtin_loaded()
    factory = _AGENTS.get(agent_type)
    if factory is None:
        raise ValueError(
            f"unknown agent type {agent_type!r}; known: {agent_types()}"
        )
    try:
        agent = factory()
    except (ImportError, AttributeError) as error:
        raise ValueError(
            f"agent type {agent_type!r} is registered but its implementation "
            f"failed to load: {error}"
        ) from error
    agent.agent_type = agent_type
    return agent


def load_class(class_name: str) -> type:
    """Load ``module.path.ClassName`` (used by custom python agents)."""
    module_name, _, cls_name = class_name.rpartition(".")
    if not module_name:
        raise ValueError(f"className must be 'module.Class', got {class_name!r}")
    module = importlib.import_module(module_name)
    return getattr(module, cls_name)


_builtin_loaded = False


def _ensure_builtin_loaded() -> None:
    global _builtin_loaded
    if _builtin_loaded:
        return
    _builtin_loaded = True
    # import for registration side effects
    from langstream_tpu import agents as _agents  # noqa: F401
