"""Efficiency accounting: roofline cost model + SLO burn-rate math.

The observability plane (PR 1) answers "where did a request's time go";
this module answers "how close to the hardware ceiling is the engine
running, and is the fleet meeting its SLOs" — the control signals every
perf PR is judged against (AIBrix / DeepServe treat MFU-style utilization
and SLO attainment as first-class scheduler inputs).

Three pieces, all analytical and dependency-free so they run identically
on a laptop and on-chip:

- :class:`PeakSpecs` — per-chip peak FLOP/s and HBM bandwidth
  (v5e-1 defaults; ``LANGSTREAM_PEAK_TFLOPS`` / ``LANGSTREAM_PEAK_HBM_GBS``
  override for other chip generations without a code change).
- :class:`CostModel` — FLOPs and HBM bytes per prefill token and per
  decode step, derived purely from the model config (layers, heads /
  kv_heads, head_dim, hidden, vocab, weight/KV quantization widths,
  dense vs paged KV layout). The engine multiplies these by measured
  chunk wall time to produce per-chunk **MFU** (model FLOP utilization)
  and **MBU** (memory-bandwidth utilization).
- :class:`SLOTracker` — multi-window (5m/1h) SLO burn rates computed
  from timestamped snapshots of the TTFT/TPOT latency histograms: the
  same ``le``-bucketed data every /metrics surface exposes, so the burn
  math is auditable from a scrape alone (:func:`violation_fraction`).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Mapping, Optional, Tuple

# v5e-1 per-chip peaks (bf16 MXU; weight-only int8 halves weight BYTES
# but the matmuls still run in bf16 — qeinsum dequantizes into the
# contraction — so the FLOPs ceiling stays the bf16 one)
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_PEAK_HBM_GBS = 819.0

ENV_PEAK_TFLOPS = "LANGSTREAM_PEAK_TFLOPS"
ENV_PEAK_HBM_GBS = "LANGSTREAM_PEAK_HBM_GBS"


@dataclasses.dataclass(frozen=True)
class PeakSpecs:
    """Per-chip hardware ceilings the roofline divides by."""

    flops: float = DEFAULT_PEAK_FLOPS
    hbm_bytes_per_s: float = DEFAULT_PEAK_HBM_GBS * 1e9

    @classmethod
    def from_env(cls) -> "PeakSpecs":
        tflops = os.environ.get(ENV_PEAK_TFLOPS, "")
        gbs = os.environ.get(ENV_PEAK_HBM_GBS, "")
        return cls(
            flops=float(tflops) * 1e12 if tflops else DEFAULT_PEAK_FLOPS,
            hbm_bytes_per_s=(
                float(gbs) * 1e9 if gbs else DEFAULT_PEAK_HBM_GBS * 1e9
            ),
        )


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Analytical FLOPs/bytes per unit of engine work.

    Derived once from the model config at engine construction; every
    accessor is a handful of integer multiplies, cheap enough to run on
    the engine thread per dispatch.

    Conventions (all counts are per CHIP — utilization against the
    single-chip peak is what the bench reports and what A/B legs
    compare; under tensor parallelism ``tp_shards`` divides the sharded
    work so a tp=2 engine is not billed whole-model FLOPs/bytes per
    chip, which would overstate MFU/MBU by ~tp×):

    - matmul FLOPs: ``2 * params`` per token (multiply+add), the
      standard serving approximation (embedding lookups excluded).
    - attention FLOPs: QK^T + AV are each ``2 * ctx * num_heads *
      head_dim`` per token per layer → ``4 * ctx * heads * head_dim *
      layers`` total. GQA shrinks the KV *bytes* (kv_heads), not the
      query-side FLOPs.
    - decode-step HBM bytes: the full weight working set streams once
      per step (batched slots share it — that is the whole point of
      batching) plus each active slot's KV history read + 1 row written.
    - paged layout: KV reads round each slot's context up to the block
      size (any block-granular access touches whole blocks), and the
      byte model is KERNEL-aware (``paged_kernel``): the fused ragged
      Pallas kernel streams each table-addressed pool block once plus
      the table/metadata words themselves, while the gather/scatter
      reference composition reads the pool, WRITES a contiguous copy,
      and re-reads that copy in the attention einsum — 3× the KV-read
      traffic. Charging both legs the same bytes would make the slower
      leg's MBU read dishonestly high (:meth:`kv_read_bytes`).
    - weight-only int8 halves weight bytes (per-channel scales are
      <1% and excluded); int8 KV stores int8 values + one f32 scale per
      (layer, position, kv_head) for each of k and v.
    - tensor parallelism (``tp_shards`` > 1): weights shard over tp
      (heads/mlp/vocab rules — the whole parameter set to the serving
      approximation), the KV cache shards on its kv-head axis, and the
      query-head FLOPs split the same way, so weight bytes, KV
      row bytes, and every FLOPs accessor divide by ``tp_shards``.
      Block tables do NOT divide: they are replicated scalar-prefetch
      operands — every shard's kernel reads the full table — so the
      per-chip table words stay whole. Activations are replicated per
      chip (and excluded from the byte model like in the dense case).
    """

    params: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    weight_bytes: int      # per chip (already divided by tp_shards)
    kv_row_bytes: int      # per chip, per token of KV history, all layers
    kv_block_size: int = 1  # paged read granularity (1 = dense)
    # paged attention kernel the engine dispatches: "fused" | "reference"
    # (None = dense layout — no table indirection to charge for)
    paged_kernel: Optional[str] = None
    # tensor-parallel shard count: FLOPs accessors divide by this
    # (weight/KV BYTES are divided once at construction)
    tp_shards: int = 1

    @classmethod
    def from_model_config(
        cls,
        config: Any,
        *,
        weight_quant: Optional[str] = None,
        kv_quant: bool = False,
        kv_block_size: int = 1,
        paged_kernel: Optional[str] = None,
        tp: int = 1,
    ) -> "CostModel":
        params = config.num_params()
        head_dim = config.dims_per_head
        tp = max(1, int(tp))
        if kv_quant:
            # int8 values + one f32 scale per (layer, pos, kv_head) for
            # each of k and v
            kv_row_bytes = 2 * config.num_layers * config.num_kv_heads * (
                head_dim + 4
            )
        else:
            kv_row_bytes = (
                2 * config.num_layers * config.num_kv_heads * head_dim * 2
            )  # k+v, bf16
        return cls(
            params=params,
            num_layers=config.num_layers,
            num_heads=config.num_heads,
            num_kv_heads=config.num_kv_heads,
            head_dim=head_dim,
            weight_bytes=params * (1 if weight_quant == "int8" else 2) // tp,
            kv_row_bytes=kv_row_bytes // tp,
            kv_block_size=max(1, int(kv_block_size)),
            paged_kernel=paged_kernel,
            tp_shards=tp,
        )

    # ------------------------------------------------------------------ #
    # decode
    # ------------------------------------------------------------------ #
    def kv_read_tokens(self, ctx: int) -> int:
        """KV history rows a decode step actually reads for one slot at
        context ``ctx`` (paged gathers touch whole blocks)."""
        block = self.kv_block_size
        return -(-ctx // block) * block if block > 1 else ctx

    def kv_read_bytes(self, kv_tokens: float) -> float:
        """HBM bytes to get ``kv_tokens`` rows of (block-padded) KV
        history in front of the compute units, per the dispatched
        kernel:

        - dense: rows stream once.
        - paged fused: pool blocks stream once through the table-
          addressed index maps, plus the table/metadata words the
          kernel prefetches (one int32 per touched block per layer —
          the pallas_call runs once per layer inside the scan).
        - paged reference: ``gather_blocks`` reads the pool AND writes
          a contiguous copy, then attention re-reads the copy — 3× the
          row bytes — plus the same table reads for the gather indices.
        """
        base = float(self.kv_row_bytes) * kv_tokens
        if self.paged_kernel is None:
            return base
        table_bytes = 4.0 * self.num_layers * (
            -(-kv_tokens // self.kv_block_size)
        )
        if self.paged_kernel == "fused":
            return base + table_bytes
        return 3.0 * base + table_bytes

    def decode_chunk_flops(
        self, steps: int, active: int, kv_tokens: int, block: int = 1
    ) -> float:
        """FLOPs for one K-step decode chunk. ``kv_tokens`` is the sum of
        active slots' context lengths at dispatch (attention cost is
        linear in the summed context, so only the sum is needed).

        ``block`` is the verify width of a speculative step (1 + spec_k;
        1 = plain decode): every matmul processes ``block`` positions per
        slot per step, and each position attends over the slot's context
        plus its own in-block causal prefix — this is exactly the
        conversion speculation sells (k× the useful FLOPs for ~1× the
        weight bytes), so MFU must bill it."""
        in_block = active * block * (block - 1) / 2.0  # causal intra-block
        per_step = (
            2.0 * self.params * active * block
            + 4.0 * (kv_tokens * block + in_block)
            * self.num_heads * self.head_dim * self.num_layers
        )
        # per-chip under tp: matmul params and query heads both shard,
        # so the whole per-step FLOPs count divides by the shard count
        return per_step * steps / self.tp_shards

    def decode_chunk_bytes(
        self, steps: int, active: int, kv_tokens: int, block: int = 1
    ) -> float:
        """HBM bytes for one K-step decode chunk: weights once per step
        + each active slot's kernel-aware KV read (:meth:`kv_read_bytes`)
        + ``block`` rows written per slot per step. ``kv_tokens`` should
        already be block-padded for the paged layout
        (:meth:`kv_read_tokens` per slot, summed).

        ``block`` > 1 (speculative verify) does NOT multiply the weight
        or KV-read streams — the whole point of verifying k drafts in
        one forward is that they share the step's weight pass — only the
        KV rows written scale with the verify width. Billing k tokens at
        1-token bytes would overstate MBU by ~k×."""
        per_step = (
            float(self.weight_bytes)
            + self.kv_read_bytes(kv_tokens)
            + float(self.kv_row_bytes) * active * block
        )
        return per_step * steps

    def kv_handoff_bytes(self, tokens: int) -> float:
        """Bytes a paged-KV handoff (prefill/decode disaggregation)
        moves for ``tokens`` rows of history: whole-model rows —
        ``kv_row_bytes`` is per CHIP under tp, and an export
        concatenates every shard's kv-heads — block-padded like any
        pool access (the handoff ships whole blocks). This is the
        transfer price the disagg A/B reads next to its tail win, and
        what the engine's ``kv_handoff_*_bytes_total`` gauges should
        roughly integrate to."""
        return (
            float(self.kv_row_bytes) * self.tp_shards
            * self.kv_read_tokens(int(tokens))
        )

    def kv_demote_bytes(self, tokens: int) -> float:
        """D2H bytes to demote ``tokens`` rows of KV history into the
        host-DRAM tier (ISSUE 18). Same whole-model, block-padded row
        accounting as :meth:`kv_handoff_bytes` — the demote gather IS
        the handoff export jit pointed at PCIe instead of the fabric —
        so the on-chip handoff-bandwidth window doubles as this leg's
        calibration. Integrates to ``kv_host_demoted_bytes_total``."""
        return self.kv_handoff_bytes(tokens)

    def kv_promote_bytes(self, tokens: int) -> float:
        """H2D bytes to promote ``tokens`` rows back into the HBM pool
        through the donated import scatter. Symmetric with
        :meth:`kv_demote_bytes` (same rows, opposite direction); the
        price a promotion pays instead of the recompute FLOPs a cold
        re-teach would burn. Integrates to
        ``kv_host_promoted_bytes_total``."""
        return self.kv_handoff_bytes(tokens)

    # ------------------------------------------------------------------ #
    # prefill
    # ------------------------------------------------------------------ #
    def prefill_flops(self, new_tokens: int, offset: int = 0) -> float:
        """FLOPs to prefill ``new_tokens`` starting at cache position
        ``offset`` (warm continuation / chunked window): matmul
        ``2·P`` per token plus causal attention over each token's own
        prefix (position p costs ``4·p·heads·head_dim`` per layer)."""
        positions_sum = (
            new_tokens * offset + new_tokens * (new_tokens - 1) // 2
        )
        return (
            2.0 * self.params * new_tokens
            + 4.0 * positions_sum * self.num_heads * self.head_dim
            * self.num_layers
        ) / self.tp_shards  # per chip: params and heads shard over tp

    def prefill_bytes(self, new_tokens: int, offset: int = 0) -> float:
        """HBM bytes for a prefill dispatch: weights once + kernel-aware
        KV prefix read + the new rows written. Prefill is FLOPs-bound at
        any real length; this exists so prefill MBU is also reportable."""
        return (
            float(self.weight_bytes)
            + self.kv_read_bytes(self.kv_read_tokens(offset))
            + float(self.kv_row_bytes) * new_tokens
        )

    # ------------------------------------------------------------------ #
    # mixed prefill+decode dispatch (prefill_mode: mixed)
    # ------------------------------------------------------------------ #
    def mixed_step_flops(
        self,
        decode_rows: int,
        decode_kv_tokens: int,
        prefill_windows,  # [(offset, new_tokens), ...]
    ) -> float:
        """FLOPs for one mixed step: the decode riders' single-step
        chunk plus each admitting row's prefill window at its offset.
        Only LIVE tokens are billed (like every other accessor) — the
        padded [S, W] grid's ghost positions burn real device FLOPs but
        modeled-useful-work-over-wall is what MFU means, so padding
        shows up as lower MFU (and in the ``prefill_padding`` goodput
        reason), never as inflated utilization."""
        flops = self.decode_chunk_flops(1, decode_rows, decode_kv_tokens)
        for offset, new_tokens in prefill_windows:
            flops += self.prefill_flops(new_tokens, offset=offset)
        return flops

    def mixed_step_bytes(
        self, kv_tokens: float, rows_written: int
    ) -> float:
        """HBM bytes for one mixed step: ONE weight pass serves every
        row — decode riders AND prefill windows share it, which is the
        fusion's whole point (the split path streams the weights once
        for the prefill dispatch and again for the decode step) — plus
        the kernel-aware KV reads (decode contexts + window prefixes,
        block-padded, summed into ``kv_tokens``) and the new rows
        written (decode tokens + prefill window tokens)."""
        return (
            float(self.weight_bytes)
            + self.kv_read_bytes(kv_tokens)
            + float(self.kv_row_bytes) * rows_written
        )

    # ------------------------------------------------------------------ #
    # utilization
    # ------------------------------------------------------------------ #
    @staticmethod
    def mfu(flops: float, wall_s: float, peaks: PeakSpecs) -> float:
        return flops / wall_s / peaks.flops if wall_s > 0 else 0.0

    @staticmethod
    def mbu(hbm_bytes: float, wall_s: float, peaks: PeakSpecs) -> float:
        return (
            hbm_bytes / wall_s / peaks.hbm_bytes_per_s if wall_s > 0 else 0.0
        )


# ---------------------------------------------------------------------- #
# SLO burn rates from histogram snapshots
# ---------------------------------------------------------------------- #
def count_le(snapshot: Mapping[str, float], target: float) -> float:
    """Observations ≤ ``target`` in a cumulative ``le``-keyed histogram
    snapshot (:meth:`api.metrics.Histogram.snapshot` shape), linearly
    interpolated inside the bucket containing ``target``. Observations
    in the +Inf bucket never count as ≤ any finite target."""
    entries = sorted(
        (float("inf") if le == "+Inf" else float(le), value)
        for le, value in snapshot.items()
        if le not in ("sum", "count")
    )
    prev_upper, prev_cum = 0.0, 0.0
    for upper, cumulative in entries:
        if target <= upper:
            if upper == float("inf"):
                # target beyond the last finite bound: everything in the
                # +Inf bucket is (conservatively) a violation
                return prev_cum
            if upper == prev_upper:
                return cumulative
            fraction = (target - prev_upper) / (upper - prev_upper)
            return prev_cum + (cumulative - prev_cum) * max(
                0.0, min(1.0, fraction)
            )
        prev_upper, prev_cum = upper, cumulative
    return prev_cum


def violation_fraction(
    now: Mapping[str, float],
    then: Optional[Mapping[str, float]],
    target: float,
) -> Optional[float]:
    """Fraction of observations ABOVE ``target`` between two snapshots
    of the same histogram (``then`` = None means since the beginning).
    Returns None when no observations landed in the interval."""
    total = now.get("count", 0) - (then.get("count", 0) if then else 0)
    if total <= 0:
        return None
    ok = count_le(now, target) - (count_le(then, target) if then else 0.0)
    return max(0.0, min(1.0, (total - ok) / total))


class SLOTracker:
    """Multi-window SLO burn rates for TTFT/TPOT targets.

    Burn rate = (violation fraction in the window) / (error budget),
    the standard SRE multi-window shape: burn 1.0 means the service is
    consuming its budget exactly as fast as the SLO allows; >1 predicts
    a breach. Computed from timestamped snapshots of the engine's
    latency histograms, so the numbers agree with what a Prometheus
    scrape of the same buckets would show.

    Targets are p95 objectives (``objective=0.95`` → 5% budget):
    ``{"ttft_ms_p95": 200, "tpot_ms_p95": 30}`` — either key optional.
    """

    WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))

    def __init__(
        self,
        targets: Mapping[str, Any],
        histograms: Mapping[str, Any],  # {"ttft": Histogram, "tpot": ...}
        *,
        objective: float = 0.95,
        snapshot_interval: float = 15.0,
    ) -> None:
        self.objective = float(objective)
        self.snapshot_interval = float(snapshot_interval)
        self.histograms = dict(histograms)
        self.targets_s: Dict[str, float] = {}
        for key in ("ttft", "tpot"):
            raw = targets.get(f"{key}_ms_p95")
            if raw and key in self.histograms:
                self.targets_s[key] = float(raw) / 1e3
        self._ring: Deque[Tuple[float, Dict[str, Dict[str, float]]]] = (
            deque()
        )
        # per-stage SLO blame (ISSUE 20): violating requests counted by
        # the journey stage that dominated the violated window, keyed
        # (kind, stage)  # guarded-by: _lock
        self._blame: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    def tick(self, now: Optional[float] = None) -> None:
        """Record a timestamped snapshot (at most one per
        ``snapshot_interval``); called per finished request and from
        :meth:`gauges`, so scraping alone keeps the windows honest."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self.snapshot_interval:
                return
            self._ring.append((
                now,
                {
                    key: self.histograms[key].snapshot()
                    for key in self.targets_s
                },
            ))
            horizon = now - self.WINDOWS[-1][1] - self.snapshot_interval
            while len(self._ring) > 1 and self._ring[1][0] <= horizon:
                self._ring.popleft()

    def attribute(self, kind: str, stage: Optional[str]) -> None:
        """Book one violating request against its dominant journey
        stage (``runtime/journey.blame_stage``) — the per-stage blame
        the burn rates alone cannot give: a burning TTFT budget with
        blame on ``queue`` is a capacity problem, on ``handoff_transit``
        a fabric problem, on ``prefill`` a scheduling one."""
        if not stage or kind not in ("ttft", "tpot"):
            return
        with self._lock:
            key = (kind, str(stage))
            self._blame[key] = self._blame.get(key, 0) + 1

    def _snapshot_before(
        self, key: str, cutoff: float
    ) -> Optional[Dict[str, float]]:
        """Newest ring snapshot taken at or before ``cutoff`` (None =
        tracker younger than the window → burn over the whole history)."""
        best = None
        for ts, snaps in self._ring:
            if ts <= cutoff:
                best = snaps.get(key)
            else:
                break
        return best

    def gauges(self, now: Optional[float] = None) -> Dict[str, float]:
        now = time.monotonic() if now is None else now
        self.tick(now)
        out: Dict[str, float] = {}
        budget = max(1e-9, 1.0 - self.objective)
        with self._lock:
            for key, target_s in self.targets_s.items():
                out[f"jax_engine_slo_{key}_p95_target_ms"] = round(
                    target_s * 1e3, 3
                )
                snap_now = self.histograms[key].snapshot()
                for label, window in self.WINDOWS:
                    then = self._snapshot_before(key, now - window)
                    fraction = violation_fraction(snap_now, then, target_s)
                    if fraction is not None:
                        out[f"jax_engine_slo_{key}_burn_rate_{label}"] = (
                            round(fraction / budget, 4)
                        )
            for (kind, stage), count in sorted(self._blame.items()):
                out[
                    "jax_engine_slo_blame_total"
                    f'{{kind="{kind}",stage="{stage}"}}'
                ] = float(count)
        return out
