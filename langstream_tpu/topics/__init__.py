"""Topic (broker) runtime implementations.

The reference ships three broker data planes — Kafka, Pulsar, Pravega
(``langstream-kafka-runtime/``, ``langstream-pulsar-runtime/``,
``langstream-pravega-runtime/``). This framework ships:

- ``memory``  — an in-process broker with Kafka-like semantics (partitions,
  consumer groups, contiguous-watermark commit). The default for local runs
  and tests, and the transport of the single-process runner
  (the reference's analogue is the noop/in-process pattern under
  ``langstream-core/.../impl/noop/`` + the runtime-tester).
- ``stream``  — a durable log-backed broker (file-backed segments) for
  multi-process deployments on one host.

Registry: look up a runtime by the ``streamingCluster.type`` value of
``instance.yaml`` (reference SPI:
``langstream-api/.../runner/topics/TopicConnectionsRuntimeRegistry.java``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from langstream_tpu.api.topics import TopicConnectionsRuntime

_FACTORIES: Dict[str, Callable[[], TopicConnectionsRuntime]] = {}


def register_topic_runtime(name: str, factory: Callable[[], TopicConnectionsRuntime]) -> None:
    _FACTORIES[name] = factory


def create_topic_runtime(streaming_cluster: Dict[str, Any]) -> TopicConnectionsRuntime:
    kind = (streaming_cluster or {}).get("type", "memory")
    if kind in ("noop", "none"):
        kind = "memory"
    factory = _FACTORIES.get(kind)
    if factory is None:
        raise ValueError(
            f"unknown streaming cluster type {kind!r}; known: {sorted(_FACTORIES)}"
        )
    return factory()


def _register_builtin() -> None:
    from langstream_tpu.topics.memory import MemoryTopicConnectionsRuntime

    register_topic_runtime("memory", MemoryTopicConnectionsRuntime)


_register_builtin()
