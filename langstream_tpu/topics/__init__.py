"""Topic (broker) runtime implementations.

The reference ships three broker data planes — Kafka, Pulsar, Pravega
(``langstream-kafka-runtime/``, ``langstream-pulsar-runtime/``,
``langstream-pravega-runtime/``). This framework ships:

- ``memory``  — an in-process broker with Kafka-like semantics (partitions,
  consumer groups, contiguous-watermark commit). The default for local runs
  and tests, and the transport of the single-process runner
  (the reference's analogue is the noop/in-process pattern under
  ``langstream-core/.../impl/noop/`` + the runtime-tester).
- ``tpulog``  — the framework's own durable partitioned log broker (native
  C++ segment store, consumer groups, persisted commit watermarks). With a
  ``directory`` configuration it runs embedded in-process; with an
  ``address`` it connects to a served broker
  (``python -m langstream_tpu broker``) for multi-process apps.

Registry: look up a runtime by the ``streamingCluster.type`` value of
``instance.yaml`` (reference SPI:
``langstream-api/.../runner/topics/TopicConnectionsRuntimeRegistry.java``).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict

from langstream_tpu.api.topics import TopicConnectionsRuntime

_FACTORIES: Dict[str, Callable[..., TopicConnectionsRuntime]] = {}


def register_topic_runtime(
    name: str, factory: Callable[..., TopicConnectionsRuntime]
) -> None:
    """Register a runtime factory. The factory receives the
    ``streamingCluster.configuration`` dict only when its first parameter
    is literally named ``configuration`` (or ``config``); any other
    factory — e.g. a runtime class whose ``__init__`` takes an optional
    broker object — is called with no arguments. Parameter *name*, not
    arity, is the contract: an arity heuristic would feed the config dict
    to factories whose first optional parameter means something else."""
    _FACTORIES[name] = factory


def _wants_configuration(factory: Callable[..., Any]) -> bool:
    try:
        params = list(inspect.signature(factory).parameters.values())
    except (TypeError, ValueError):
        return False
    return bool(params) and params[0].name in ("configuration", "config")


def create_topic_runtime(streaming_cluster: Dict[str, Any]) -> TopicConnectionsRuntime:
    kind = (streaming_cluster or {}).get("type", "memory")
    if kind in ("noop", "none"):
        kind = "memory"
    factory = _FACTORIES.get(kind)
    if factory is None:
        raise ValueError(
            f"unknown streaming cluster type {kind!r}; known: {sorted(_FACTORIES)}"
        )
    configuration = (streaming_cluster or {}).get("configuration", {}) or {}
    if _wants_configuration(factory):
        return factory(configuration)
    return factory()


def _make_tpulog(configuration: Dict[str, Any]) -> TopicConnectionsRuntime:
    if configuration.get("address"):
        from langstream_tpu.topics.log.client import (
            RemoteTopicConnectionsRuntime,
        )

        return RemoteTopicConnectionsRuntime(configuration["address"])
    directory = configuration.get("directory")
    if not directory:
        raise ValueError(
            "tpulog streamingCluster needs a configuration with either "
            "'address' (served broker) or 'directory' (embedded broker); "
            f"got {sorted(configuration)}"
        )
    from langstream_tpu.topics.log.broker import LogTopicConnectionsRuntime

    return LogTopicConnectionsRuntime(root=str(directory))


def _make_kafka(configuration: Dict[str, Any]) -> TopicConnectionsRuntime:
    from langstream_tpu.topics.kafka.runtime import (
        KafkaTopicConnectionsRuntime,
    )

    return KafkaTopicConnectionsRuntime(configuration)


def _make_pulsar(configuration: Dict[str, Any]) -> TopicConnectionsRuntime:
    from langstream_tpu.topics.pulsar import PulsarTopicConnectionsRuntime

    return PulsarTopicConnectionsRuntime(configuration)


def _make_pravega(configuration: Dict[str, Any]) -> TopicConnectionsRuntime:
    from langstream_tpu.topics.pravega import PravegaTopicConnectionsRuntime

    return PravegaTopicConnectionsRuntime(configuration)


def _register_builtin() -> None:
    from langstream_tpu.topics.memory import MemoryTopicConnectionsRuntime

    register_topic_runtime("memory", lambda configuration=None: MemoryTopicConnectionsRuntime())
    register_topic_runtime("tpulog", _make_tpulog)
    register_topic_runtime("kafka", _make_kafka)
    register_topic_runtime("pulsar", _make_pulsar)
    register_topic_runtime("pravega", _make_pravega)


_register_builtin()
