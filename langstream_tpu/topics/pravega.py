"""Pravega topic runtime over the official ``pravega`` Python client.

Reference: ``langstream-pravega-runtime/src/main/java/ai/langstream/
pravega/PravegaTopicConnectionsRuntimeProvider.java`` — a thin adapter
over ``io.pravega.client``. Wire compatibility is kept exactly:

- events are UTF-8 JSON strings shaped
  ``{"key":…, "value":…, "headers":{name: value}, "timestamp": millis}``
  (``RecordWrapper``, provider:505-508); the routing key is the record
  key stringified (``serialiseKey``, provider:483-493). Values that are
  bytes travel base64-encoded (what Jackson does with ``byte[]`` on the
  Java side); no extra fields are added because the reference's record
  deserializer rejects unknown properties.
- consumers are reader groups named by the agent's group; Pravega's
  reader-group position tracking owns redelivery, so ``commit`` is a
  broker-side no-op — same contract as the reference, whose consumer
  also issues no per-event acks.
- readers use an ephemeral ``reader-<uuid>`` group (provider:112-115);
  like the reference, recovering an absolute ``initialPosition`` is not
  supported (its TODO at provider:118).
- admin maps ``TopicSpec`` to create-scope + create-stream with fixed
  scaling = partitions, and delete to seal + delete.

The Pravega wire protocol is binary (protobuf gRPC controller + custom
segment-store framing) with no offline spec, so unlike Kafka (where the
framework implements the protocol from scratch) this runtime needs the
client library: ``pip install pravega`` (Rust-native bindings). The
module import-gates on it with a clear error; every piece of adapter
logic (envelope codec, group naming, slice draining, admin mapping) is
tested lib-free against an in-memory fake client (tests/pravega_mock.py).

Config (``streamingCluster.configuration``), mirroring
``PravegaClientUtils.java:37-82``:

- ``client.controller-uri`` — default ``tcp://localhost:9090``
- ``client.scope``          — default ``langstream``
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import json
import logging
import uuid
from typing import Any, Dict, List, Optional

from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicProducer,
    TopicReader,
    TopicSpec,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------- #
# envelope codec (RecordWrapper wire shape)
# ---------------------------------------------------------------------- #
def _jsonable(value: Any) -> Any:
    if isinstance(value, bytes):
        return base64.b64encode(value).decode()
    return value


def serialise_key(key: Any) -> Optional[str]:
    """Routing key, reference ``serialiseKey``: None stays None,
    strings/numbers stringify, anything else JSON-serializes. Spellings
    match the Java side exactly (``true``/``false``, compact JSON) so
    mixed Java/Python producers route the same key to the same
    segment."""
    if key is None:
        return None
    if isinstance(key, bytes):
        return base64.b64encode(key).decode()
    if isinstance(key, bool):
        return "true" if key else "false"
    if isinstance(key, (str, int, float)):
        return str(key)
    return json.dumps(key, separators=(",", ":"))


def encode_event(record: Record) -> str:
    headers = {name: _jsonable(value) for name, value in (record.headers or [])}
    return json.dumps({
        "key": _jsonable(record.key),
        "value": _jsonable(record.value),
        "headers": headers,
        "timestamp": record.timestamp or now_millis(),
    })


def decode_event(payload: str, topic: str) -> Record:
    wrapper = json.loads(payload)
    return Record(
        key=wrapper.get("key"),
        value=wrapper.get("value"),
        headers=tuple(sorted((wrapper.get("headers") or {}).items())),
        origin=topic,
        timestamp=wrapper.get("timestamp"),
    )


def _client_module(injected: Any = None):
    if injected is not None:
        return injected
    try:
        import pravega_client  # type: ignore
    except ImportError as error:
        raise RuntimeError(
            "the 'pravega' streaming cluster needs the Pravega client "
            "bindings (pip install pravega); its wire protocol is binary "
            "and cannot be spoken without them"
        ) from error
    return pravega_client


def _config(configuration: Dict[str, Any]) -> Dict[str, Any]:
    return dict((configuration or {}).get("client") or {})


class PravegaTopicProducer(TopicProducer):
    def __init__(self, runtime: "PravegaTopicConnectionsRuntime",
                 topic: str) -> None:
        self._runtime = runtime
        self._topic = topic
        self._writer = None
        self._total = 0

    @property
    def topic(self) -> str:
        return self._topic

    async def start(self) -> None:
        if self._writer is None:
            manager = self._runtime.manager()
            self._writer = await asyncio.to_thread(
                manager.create_writer, self._runtime.scope, self._topic
            )

    async def write(self, record: Record) -> None:
        if self._writer is None:  # tolerate write-before-start like the
            await self.start()    # memory/tpulog producers do
        payload = encode_event(record)
        key = serialise_key(record.key)

        def send():
            if key is not None:
                self._writer.write_event(payload, routing_key=key)
            else:
                self._writer.write_event(payload)
            flush = getattr(self._writer, "flush", None)
            if flush is not None:
                flush()

        await asyncio.to_thread(send)
        self._total += 1

    async def close(self) -> None:
        if self._writer is not None:
            close = getattr(self._writer, "close", None)
            if close is not None:
                await asyncio.to_thread(close)
            self._writer = None

    def total_in(self) -> int:
        return self._total


class _GroupReader:
    """Shared slice-draining logic for consumers and readers."""

    def __init__(self, runtime: "PravegaTopicConnectionsRuntime",
                 topic: str, group: str) -> None:
        self._runtime = runtime
        self._topic = topic
        self._group = group
        self._reader = None
        self._buffer: List[Record] = []
        # in-flight drain (the real bindings' get_segment_slice can
        # block past the poll timeout); kept across read() calls so a
        # drain finishing after a timeout is never dropped
        self._pending: Optional[asyncio.Task] = None
        self.total = 0

    async def start(self) -> None:
        if self._reader is not None:
            return
        manager = self._runtime.manager()
        scope = self._runtime.scope

        def bring_up():
            group = manager.create_reader_group(self._group, scope, self._topic)
            return group.create_reader(f"reader-{uuid.uuid4()}")

        self._reader = await asyncio.to_thread(bring_up)

    def _drain(self) -> List[Record]:
        records: List[Record] = []
        slice_ = self._reader.get_segment_slice()
        if slice_ is None:
            return records
        for event in slice_:
            records.append(
                decode_event(
                    bytes(event.data()).decode("utf-8"), self._topic
                )
            )
        release = getattr(self._reader, "release_segment", None)
        if release is not None:
            release(slice_)
        return records

    async def read(self, max_records: int, timeout: float) -> List[Record]:
        if self._reader is None:
            await self.start()
        started = asyncio.get_event_loop().time()
        if not self._buffer:
            if self._pending is None:
                self._pending = asyncio.ensure_future(
                    asyncio.to_thread(self._drain)
                )
            try:
                self._buffer.extend(
                    await asyncio.wait_for(
                        asyncio.shield(self._pending), timeout
                    )
                )
                self._pending = None
            except asyncio.TimeoutError:
                return []  # drain keeps running; next read() awaits it
            except BaseException:
                # a failed drain task must not be re-awaited forever: a
                # transient client error would wedge the consumer on the
                # same stale exception. Drop it so the next read()
                # starts a fresh drain.
                self._pending = None
                raise
        if not self._buffer:
            # empty slice returned instantly: spend the rest of the poll
            # timeout idle, or the runner loop busy-spins (the other
            # runtimes block inside their own wait_for_data)
            remaining = timeout - (asyncio.get_event_loop().time() - started)
            if remaining > 0:
                await asyncio.sleep(remaining)
            return []
        out, self._buffer = (
            self._buffer[:max_records], self._buffer[max_records:]
        )
        self.total += len(out)
        return out

    async def close(self) -> None:
        if self._pending is not None:
            # wait for the in-flight drain thread: cancelling cannot
            # stop a to_thread worker, and taking the reader offline
            # while the thread still uses it is undefined behavior in
            # the native bindings. Bounded — a drain blocked past this
            # is abandoned (best effort; the bindings own the socket).
            try:
                await asyncio.wait_for(asyncio.shield(self._pending), 5.0)
            except (asyncio.TimeoutError, Exception):
                logger.warning(
                    "pravega: drain still in flight at close; abandoning"
                )
            self._pending = None
        if self._reader is not None:
            offline = getattr(self._reader, "reader_offline", None)
            if offline is not None:
                await asyncio.to_thread(offline)
            self._reader = None


class PravegaTopicConsumer(TopicConsumer):
    """Reader group named by the agent group: processes sharing the
    group share the stream's segments; the group's server-side position
    owns redelivery (hence commit() is a no-op, like the reference)."""

    def __init__(self, runtime, topic: str, group: str) -> None:
        self._inner = _GroupReader(runtime, topic, group)

    async def start(self) -> None:
        await self._inner.start()

    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        return await self._inner.read(max_records, timeout)

    async def commit(self, records: List[Record]) -> None:
        return None

    async def close(self) -> None:
        await self._inner.close()

    def total_out(self) -> int:
        return self._inner.total


class PravegaTopicReader(TopicReader):
    """Ephemeral reader group — tails without a durable position."""

    def __init__(self, runtime, topic: str,
                 initial_position: OffsetPosition) -> None:
        if initial_position is OffsetPosition.LATEST:
            logger.warning(
                "pravega reader: LATEST start is approximated by a fresh "
                "reader group from the stream head (reference TODO: "
                "PravegaTopicConnectionsRuntimeProvider.java:118)"
            )
        self._inner = _GroupReader(
            runtime, topic, f"reader-{uuid.uuid4().hex[:16]}"
        )

    async def start(self) -> None:
        await self._inner.start()

    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        return await self._inner.read(max_records, timeout)

    async def close(self) -> None:
        await self._inner.close()


class PravegaTopicAdmin(TopicAdmin):
    def __init__(self, runtime: "PravegaTopicConnectionsRuntime") -> None:
        self._runtime = runtime

    @staticmethod
    def _create_if_absent(fn, *args) -> None:
        """Run a create call tolerating only the already-exists outcome
        (the bindings either return False or raise with 'exists' in the
        message); anything else — controller unreachable, auth — must
        surface, not masquerade as success."""
        try:
            fn(*args)
        except Exception as error:
            if "exist" in str(error).lower():
                return
            raise

    async def create_topic(self, spec: TopicSpec) -> None:
        if spec.creation_mode != "create-if-not-exists":
            return
        manager = self._runtime.manager()
        scope = self._runtime.scope

        def create():
            self._create_if_absent(manager.create_scope, scope)
            self._create_if_absent(
                manager.create_stream, scope, spec.name,
                max(spec.partitions, 1),
            )

        await asyncio.to_thread(create)

    async def delete_topic(self, name: str) -> None:
        manager = self._runtime.manager()
        scope = self._runtime.scope

        def delete():
            # broad tolerance IS the reference behavior here ("Topic
            # didn't exit. Not a problem", provider:440-443)
            try:
                seal = getattr(manager, "seal_stream", None)
                if seal is not None:
                    seal(scope, name)
                manager.delete_stream(scope, name)
            except Exception:
                logger.info("pravega stream %s didn't exist", name)

        await asyncio.to_thread(delete)

    async def close(self) -> None:
        return None


class PravegaTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self, configuration: Optional[Dict[str, Any]] = None,
                 client_module: Any = None) -> None:
        client = _config(configuration or {})
        self.controller_uri = (
            client.get("controller-uri")
            or client.get("controllerUri")
            or "tcp://localhost:9090"
        )
        self.scope = client.get("scope") or "langstream"
        self._client_module = client_module
        self._manager = None

    def manager(self):
        if self._manager is None:
            module = _client_module(self._client_module)
            self._manager = module.StreamManager(self.controller_uri)
        return self._manager

    def create_consumer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicConsumer:
        return PravegaTopicConsumer(
            self, config["topic"],
            config.get("group") or agent_id or f"group-{uuid.uuid4().hex[:8]}",
        )

    def create_producer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicProducer:
        return PravegaTopicProducer(self, config["topic"])

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        return PravegaTopicReader(self, config["topic"], initial_position)

    def create_admin(self) -> TopicAdmin:
        return PravegaTopicAdmin(self)

    async def init(self, streaming_cluster_config: Dict[str, Any]) -> None:
        return None

    async def close(self) -> None:
        self._manager = None
