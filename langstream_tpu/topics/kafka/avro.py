"""Avro binary codec + Confluent Schema Registry client (no avro lib).

Reference: the schema plumbing in ``langstream-agents-commons`` (Avro
``GenericRecord`` converters + schema-registry serializers) that lets
reference pipelines consume records produced by the wider Kafka
ecosystem. Scope here:

- the Avro **binary** encoding for the common type lattice: null,
  boolean, int, long, float, double, bytes, string, record, enum,
  array, map, union, fixed (zigzag varints per the spec);
- the Confluent wire format: ``0x00 magic + 4-byte big-endian schema id
  + avro payload``;
- a minimal async Schema Registry REST client with an id cache.

The Kafka consumer uses this to decode foreign (non-envelope) records
into plain dict/list/scalar values when ``schemaRegistryUrl`` is
configured; producers can publish Confluent-framed Avro with
``encode_confluent``.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

CONFLUENT_MAGIC = 0


# ---------------------------------------------------------------------- #
# primitives
# ---------------------------------------------------------------------- #
class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise EOFError("truncated avro payload")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def zigzag(self) -> int:
        shift = value = 0
        while True:
            byte = self.take(1)[0]
            value |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return (value >> 1) ^ -(value & 1)
            shift += 7


def _write_zigzag(out: bytearray, value: int) -> None:
    encoded = (value << 1) ^ (value >> 63)
    while True:
        byte = encoded & 0x7F
        encoded >>= 7
        if encoded:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return


# ---------------------------------------------------------------------- #
# schema handling
# ---------------------------------------------------------------------- #
def parse_schema(schema: Any) -> Any:
    """Accept a JSON string or already-parsed schema document."""
    if isinstance(schema, str):
        try:
            return json.loads(schema)
        except ValueError:
            return schema  # a bare primitive name like "string"
    return schema


def _schema_type(schema: Any) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


# ---------------------------------------------------------------------- #
# decode
# ---------------------------------------------------------------------- #
def decode(schema: Any, reader: "_Reader") -> Any:
    kind = _schema_type(schema)
    if kind == "null":
        return None
    if kind == "boolean":
        return reader.take(1)[0] != 0
    if kind in ("int", "long"):
        return reader.zigzag()
    if kind == "float":
        return struct.unpack("<f", reader.take(4))[0]
    if kind == "double":
        return struct.unpack("<d", reader.take(8))[0]
    if kind == "bytes":
        return bytes(reader.take(reader.zigzag()))
    if kind == "string":
        return reader.take(reader.zigzag()).decode("utf-8")
    if kind == "fixed":
        return bytes(reader.take(schema["size"]))
    if kind == "enum":
        return schema["symbols"][reader.zigzag()]
    if kind == "union":
        return decode(schema[reader.zigzag()], reader)
    if kind == "array":
        out: List[Any] = []
        while True:
            count = reader.zigzag()
            if count == 0:
                return out
            if count < 0:  # block with byte size prefix
                count = -count
                reader.zigzag()
            for _ in range(count):
                out.append(decode(schema["items"], reader))
    if kind == "map":
        result: Dict[str, Any] = {}
        while True:
            count = reader.zigzag()
            if count == 0:
                return result
            if count < 0:
                count = -count
                reader.zigzag()
            for _ in range(count):
                key = reader.take(reader.zigzag()).decode("utf-8")
                result[key] = decode(schema["values"], reader)
    if kind == "record":
        record: Dict[str, Any] = {}
        for field in schema["fields"]:
            record[field["name"]] = decode(field["type"], reader)
        return record
    raise ValueError(f"unsupported avro type {kind!r}")


def decode_bytes(schema: Any, payload: bytes) -> Any:
    return decode(parse_schema(schema), _Reader(payload))


# ---------------------------------------------------------------------- #
# encode
# ---------------------------------------------------------------------- #
def encode(schema: Any, value: Any, out: Optional[bytearray] = None) -> bytes:
    if out is None:
        out = bytearray()
    kind = _schema_type(schema)
    if kind == "null":
        pass
    elif kind == "boolean":
        out.append(1 if value else 0)
    elif kind in ("int", "long"):
        _write_zigzag(out, int(value))
    elif kind == "float":
        out += struct.pack("<f", float(value))
    elif kind == "double":
        out += struct.pack("<d", float(value))
    elif kind == "bytes":
        _write_zigzag(out, len(value))
        out += value
    elif kind == "string":
        data = str(value).encode("utf-8")
        _write_zigzag(out, len(data))
        out += data
    elif kind == "fixed":
        if len(value) != schema["size"]:
            raise ValueError("fixed size mismatch")
        out += value
    elif kind == "enum":
        _write_zigzag(out, schema["symbols"].index(value))
    elif kind == "union":
        index = _pick_union_branch(schema, value)
        _write_zigzag(out, index)
        encode(schema[index], value, out)
    elif kind == "array":
        if value:
            _write_zigzag(out, len(value))
            for item in value:
                encode(schema["items"], item, out)
        _write_zigzag(out, 0)
    elif kind == "map":
        if value:
            _write_zigzag(out, len(value))
            for key, item in value.items():
                data = str(key).encode("utf-8")
                _write_zigzag(out, len(data))
                out += data
                encode(schema["values"], item, out)
        _write_zigzag(out, 0)
    elif kind == "record":
        for field in schema["fields"]:
            if field["name"] in value:
                encode(field["type"], value[field["name"]], out)
            elif "default" in field:
                encode(field["type"], field["default"], out)
            else:
                raise ValueError(f"missing record field {field['name']!r}")
    else:
        raise ValueError(f"unsupported avro type {kind!r}")
    return bytes(out)


def _pick_union_branch(union: List[Any], value: Any) -> int:
    def matches(schema: Any) -> bool:
        kind = _schema_type(schema)
        if value is None:
            return kind == "null"
        if isinstance(value, bool):
            return kind == "boolean"
        if isinstance(value, int):
            return kind in ("int", "long")
        if isinstance(value, float):
            return kind in ("float", "double")
        if isinstance(value, bytes):
            return kind in ("bytes", "fixed")
        if isinstance(value, str):
            return kind in ("string", "enum")
        if isinstance(value, list):
            return kind == "array"
        if isinstance(value, dict):
            return kind in ("record", "map")
        return False

    for index, branch in enumerate(union):
        if matches(branch):
            return index
    raise ValueError(f"no union branch for {type(value).__name__}")


# ---------------------------------------------------------------------- #
# confluent wire format + registry
# ---------------------------------------------------------------------- #
def is_confluent_framed(payload: Optional[bytes]) -> bool:
    return (
        isinstance(payload, (bytes, bytearray))
        and len(payload) >= 5
        and payload[0] == CONFLUENT_MAGIC
    )


def split_confluent(payload: bytes) -> Tuple[int, bytes]:
    schema_id = struct.unpack(">I", payload[1:5])[0]
    return schema_id, payload[5:]


def encode_confluent(schema_id: int, schema: Any, value: Any) -> bytes:
    return (
        bytes([CONFLUENT_MAGIC])
        + struct.pack(">I", schema_id)
        + encode(parse_schema(schema), value)
    )


class SchemaRegistryClient:
    """Minimal Confluent Schema Registry REST client (id-cached)."""

    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self._by_id: Dict[int, Any] = {}
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def get_schema(self, schema_id: int) -> Any:
        if schema_id in self._by_id:
            return self._by_id[schema_id]
        session = await self._get_session()
        async with session.get(
            f"{self.url}/schemas/ids/{schema_id}"
        ) as response:
            if response.status >= 300:
                raise IOError(
                    f"schema registry HTTP {response.status} for id "
                    f"{schema_id}"
                )
            payload = await response.json(content_type=None)
        schema = parse_schema(payload["schema"])
        self._by_id[schema_id] = schema
        return schema

    async def register(self, subject: str, schema: Any) -> int:
        session = await self._get_session()
        body = {"schema": json.dumps(parse_schema(schema))}
        async with session.post(
            f"{self.url}/subjects/{subject}/versions", json=body
        ) as response:
            if response.status >= 300:
                raise IOError(
                    f"schema registry HTTP {response.status} registering "
                    f"{subject}"
                )
            payload = await response.json(content_type=None)
        return int(payload["id"])

    async def decode_value(self, payload: bytes) -> Any:
        schema_id, body = split_confluent(payload)
        schema = await self.get_schema(schema_id)
        return decode(schema, _Reader(body))

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
