"""Kafka implementation of the Topic SPI.

Reference: ``KafkaTopicConnectionsRuntime.java:53`` (producer/consumer/
reader/admin factories) and ``KafkaConsumerWrapper.java:52-230`` — the
out-of-order ack bookkeeping is reproduced here: every delivered offset
is tracked, acks land in a per-partition set, and the *committed* offset
only advances across the contiguous prefix, so a crash never skips an
in-flight record (at-least-once).

Serialization: values/keys/headers use a typed envelope in one Kafka
header (``ls-meta``) so Python payloads (str/bytes/dict/...) round-trip;
foreign records (no envelope) decode as UTF-8 text, falling back to raw
bytes — the same contract the reference gets from configurable Kafka
serializers.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicProducer,
    TopicReader,
    TopicSpec,
)
from langstream_tpu.topics.kafka import avro as avro_codec
from langstream_tpu.topics.kafka import protocol as proto
from langstream_tpu.topics.kafka.client import KafkaClient

logger = logging.getLogger(__name__)

EARLIEST, LATEST = -2, -1


# ---------------------------------------------------------------------- #
# record (de)serialization (shared envelope: topics/serde.py)
# ---------------------------------------------------------------------- #
from langstream_tpu.topics.serde import (  # noqa: E402
    decode_payload as _decode_payload,
    encode_payload as _encode_payload,
)


def encode_record(record: Record) -> Tuple[
    Optional[bytes], Optional[bytes], List[Tuple[str, Optional[bytes]]]
]:
    key, key_kind = _encode_payload(record.key)
    value, value_kind = _encode_payload(record.value)
    headers: List[Tuple[str, Optional[bytes]]] = []
    header_kinds: Dict[str, str] = {}
    for name, hvalue in record.headers:
        data, kind = _encode_payload(hvalue)
        headers.append((name, data))
        header_kinds[name] = kind
    meta = json.dumps({"v": value_kind, "k": key_kind, "h": header_kinds})
    headers.append(("ls-meta", meta.encode("utf-8")))
    return key, value, headers


def decode_record(
    kafka_record: proto.KafkaRecord, topic: str
) -> "KafkaRecordView":
    kinds: Dict[str, Any] = {}
    headers: List[Tuple[str, Any]] = []
    raw_headers = []
    for name, data in kafka_record.headers:
        if name == "ls-meta" and data is not None:
            try:
                kinds = json.loads(data.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                kinds = {}
        else:
            raw_headers.append((name, data))
    header_kinds = kinds.get("h", {})
    for name, data in raw_headers:
        headers.append((name, _decode_payload(data, header_kinds.get(name))))
    return KafkaRecordView(
        value=_decode_payload(kafka_record.value, kinds.get("v")),
        key=_decode_payload(kafka_record.key, kinds.get("k")),
        origin=topic,
        timestamp=kafka_record.timestamp,
        headers=tuple(headers),
        partition=-1,  # caller fills in
        offset=kafka_record.offset,
    )


import dataclasses as _dataclasses


@_dataclasses.dataclass(frozen=True)
class KafkaRecordView(Record):
    """A Record plus its Kafka coordinates (what commit() needs)."""

    partition: int = -1
    offset: int = -1




async def _maybe_avro(registry, kafka_record, view):
    """Decode a FOREIGN Confluent-framed Avro value into plain Python
    (records produced by this framework carry the ls-meta envelope and
    are never reinterpreted)."""
    if registry is None:
        return view
    if any(name == "ls-meta" for name, _ in kafka_record.headers):
        return view
    if not avro_codec.is_confluent_framed(kafka_record.value):
        return view
    try:
        value = await registry.decode_value(kafka_record.value)
    except Exception:  # noqa: BLE001 — undecodable: keep raw bytes
        logger.exception("confluent avro decode failed; passing raw bytes")
        return view
    return _dataclasses.replace(view, value=value)


# ---------------------------------------------------------------------- #
# producer
# ---------------------------------------------------------------------- #
class KafkaTopicProducer(TopicProducer):
    """Micro-batching producer: concurrent ``write()``s within the linger
    window coalesce into one record batch per partition (the reference
    relies on the Kafka client's linger.ms/batch.size for the same);
    every ``write()`` still awaits its own batch's broker ack, so the
    durability contract (await = acked) is unchanged."""

    def __init__(
        self, client: KafkaClient, topic: str,
        *, linger: float = 0.002, batch_max: int = 256,
        value_schema: Optional[Any] = None,
        registry: Optional[avro_codec.SchemaRegistryClient] = None,
        subject: Optional[str] = None,
    ) -> None:
        self._client = client
        self._topic = topic
        self._linger = linger
        self._batch_max = batch_max
        # declared avro topic schema + registry → publish Confluent-
        # framed values foreign consumers understand (no ls-meta
        # envelope); lazily registered under <topic>-value
        self._value_schema = value_schema
        self._registry = registry
        self._subject = subject or f"{topic}-value"
        self._schema_id: Optional[int] = None
        # plain schema types publish WITHOUT the envelope: string/json/
        # bytes values any foreign consumer reads directly
        self._plain_type: Optional[str] = None
        self._written = 0
        self._round_robin = 0
        # partition -> [((key, value, headers, ts), future)]
        self._buffers: Dict[int, List] = {}
        self._flush_tasks: Dict[int, asyncio.Task] = {}

    @property
    def topic(self) -> str:
        return self._topic

    async def start(self) -> None:
        await self._client.partitions_for(self._topic)

    async def write(self, record: Record) -> None:
        partitions = await self._client.partitions_for(self._topic)
        if not partitions:
            raise proto.KafkaProtocolError(
                proto.UNKNOWN_TOPIC_OR_PARTITION, self._topic
            )
        if self._plain_type is not None:
            if self._plain_type == "string":
                value = (
                    record.value.encode("utf-8")
                    if isinstance(record.value, str)
                    else json.dumps(record.value).encode("utf-8")
                )
            elif self._plain_type == "json":
                value = json.dumps(record.value).encode("utf-8")
            else:  # bytes
                value = (
                    record.value
                    if isinstance(record.value, (bytes, bytearray))
                    else str(record.value).encode("utf-8")
                )
            key = (
                str(record.key).encode("utf-8")
                if record.key is not None else None
            )
            headers = []
            for name, hvalue in record.headers:
                data, _kind = _encode_payload(hvalue)
                headers.append((name, data))
        elif self._value_schema is not None and self._registry is not None:
            if self._schema_id is None:
                self._schema_id = await self._registry.register(
                    self._subject, self._value_schema
                )
            value = avro_codec.encode_confluent(
                self._schema_id, self._value_schema, record.value
            )
            key = (
                str(record.key).encode("utf-8")
                if record.key is not None else None
            )
            headers = []
            for name, hvalue in record.headers:
                data, _kind = _encode_payload(hvalue)
                headers.append((name, data))
        else:
            key, value, headers = encode_record(record)
        if record.key is not None:
            # stable key → partition affinity (session/KV locality rides
            # partitioning, like the reference's keyed producer). crc32 is
            # process-stable — Python's hash() is salted per interpreter
            index = zlib.crc32(str(record.key).encode("utf-8")) % len(
                partitions
            )
        else:
            index = self._round_robin % len(partitions)
            self._round_robin += 1
        partition = partitions[index]
        timestamp = record.timestamp or now_millis()
        future = asyncio.get_running_loop().create_future()
        rows = self._buffers.setdefault(partition, [])
        rows.append(((key, value, headers, timestamp), future))
        if len(rows) >= self._batch_max:
            await self._flush(partition)
        elif partition not in self._flush_tasks:
            self._flush_tasks[partition] = (
                asyncio.get_running_loop().create_task(
                    self._flush_later(partition)
                )
            )
        await future
        self._written += 1

    async def _flush_later(self, partition: int) -> None:
        await asyncio.sleep(self._linger)
        await self._flush(partition)

    async def _flush(self, partition: int) -> None:
        task = self._flush_tasks.pop(partition, None)
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        rows = self._buffers.pop(partition, [])
        if not rows:
            return
        batch = proto.encode_record_batch([payload for payload, _ in rows])
        try:
            await self._client.produce(self._topic, partition, batch)
        except BaseException as error:  # noqa: BLE001 — fail every waiter
            # the error travels via the futures (every write() awaits one);
            # not re-raised here so a timer-triggered flush doesn't also
            # log an unretrieved task exception
            for _, future in rows:
                if not future.done():
                    future.set_exception(error)
            return
        for _, future in rows:
            if not future.done():
                future.set_result(None)

    async def close(self) -> None:
        for partition in list(self._buffers):
            await self._flush(partition)
        for task in self._flush_tasks.values():
            task.cancel()
        self._flush_tasks.clear()

    def total_in(self) -> int:
        return self._written


# ---------------------------------------------------------------------- #
# consumer (group member, contiguous-watermark commit)
# ---------------------------------------------------------------------- #
class KafkaTopicConsumer(TopicConsumer):
    def __init__(
        self,
        client: KafkaClient,
        topic: str,
        group: str,
        *,
        session_timeout_ms: int = 10000,
        heartbeat_interval: float = 3.0,
        auto_offset_reset: int = EARLIEST,
        registry: Optional[avro_codec.SchemaRegistryClient] = None,
    ) -> None:
        self._client = client
        self._topic = topic
        self._group = group
        self._registry = registry
        self._session_timeout_ms = session_timeout_ms
        self._heartbeat_interval = heartbeat_interval
        self._auto_offset_reset = auto_offset_reset

        self._coordinator: int = -1
        self._member_id = ""
        self._generation = -1
        self._assignment: List[int] = []         # partitions of _topic
        self._fetch_pos: Dict[int, int] = {}     # next offset to fetch
        self._committed: Dict[int, int] = {}     # durable commit watermark
        # delivered-but-unacked offsets per partition, plus the offset
        # just past the last delivered record: the watermark is
        # min(outstanding) or, with nothing outstanding, next-after-
        # delivered. Using *delivered* offsets (not offset arithmetic)
        # keeps gaps — compaction, transaction markers — from stalling it
        self._outstanding: Dict[int, set] = {}
        self._next_after_delivered: Dict[int, int] = {}
        self._rejoin_needed = False
        self._coord_conn = None  # dedicated coordinator channel
        self._heartbeat_task: Optional[asyncio.Task] = None
        # serializes membership changes (join/rejoin) against read():
        # the heartbeat task rejoins PROMPTLY on a rebalance signal even
        # when the owner isn't polling (e.g. during app bring-up)
        self._membership_lock = asyncio.Lock()
        self._fetch_cursor = 0
        self._delivered = 0
        self._started = False
        # commit coalescing: watermark advances collect here and flush
        # to the coordinator at most every _commit_interval (plus on
        # close and before any rejoin) — the runner acks once per source
        # record, which would otherwise be one OffsetCommit RPC each
        self._commit_dirty: Dict[Tuple[str, int], int] = {}
        self._last_commit_flush = 0.0
        self._commit_interval = 0.1

    # -- membership ----------------------------------------------------- #
    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        async with self._membership_lock:
            await self._join()
        self._heartbeat_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop()
        )

    async def _reconnect_coordinator(self) -> None:
        if self._coord_conn is not None:
            await self._coord_conn.close()
        self._coordinator = await self._client.find_coordinator(self._group)
        self._coord_conn = self._client.dedicated_connection(self._coordinator)

    async def _join(self) -> None:
        # push pending watermark advances under the OLD generation first:
        # after the rebalance they would be rejected (ILLEGAL_GENERATION)
        # and the work they represent re-delivered unnecessarily
        try:
            await self._flush_commits_locked(force=True)
        except Exception:  # noqa: BLE001 — redelivery-safe to drop
            self._commit_dirty.clear()
        await self._reconnect_coordinator()
        for attempt in range(10):
            try:
                joined = await self._client.join_group(
                    self._coordinator, self._group, self._member_id,
                    [self._topic],
                    session_timeout_ms=self._session_timeout_ms,
                    conn=self._coord_conn,
                )
            except proto.KafkaProtocolError as error:
                if error.code == proto.MEMBER_ID_REQUIRED:
                    # KIP-394: adopt the broker-assigned id for the retry
                    self._member_id = getattr(error, "member_id", "") or ""
                    continue
                if error.code == proto.REBALANCE_IN_PROGRESS:
                    await asyncio.sleep(0.1)
                    continue
                if error.code in (
                    proto.NOT_COORDINATOR, proto.COORDINATOR_NOT_AVAILABLE,
                ):
                    await asyncio.sleep(0.2)
                    await self._reconnect_coordinator()
                    continue
                if error.code == proto.UNKNOWN_MEMBER_ID:
                    self._member_id = ""
                    continue
                raise
            self._member_id = joined["member_id"]
            self._generation = joined["generation"]
            assignments = None
            if joined["leader"] == self._member_id:
                partitions_by_topic: Dict[str, int] = {}
                for _mid, topics in joined["members"]:
                    for topic in topics:
                        partitions_by_topic[topic] = len(
                            await self._client.partitions_for(topic)
                        )
                assignments = proto.range_assign(
                    joined["members"], partitions_by_topic
                )
            try:
                my_assignment = await self._client.sync_group(
                    self._coordinator, self._group, self._generation,
                    self._member_id, assignments, conn=self._coord_conn,
                )
            except proto.KafkaProtocolError as error:
                if error.code in (
                    proto.REBALANCE_IN_PROGRESS, proto.ILLEGAL_GENERATION,
                ):
                    continue
                raise
            self._assignment = sorted(my_assignment.get(self._topic, []))
            await self._reset_positions()
            self._rejoin_needed = False
            logger.info(
                "kafka consumer %s joined %s gen %d: partitions %s",
                self._member_id, self._group, self._generation,
                self._assignment,
            )
            return
        raise proto.KafkaProtocolError(
            proto.REBALANCE_IN_PROGRESS, f"join retries exhausted {self._group}"
        )

    async def _reset_positions(self) -> None:
        """Start every assigned partition at the group's committed offset
        (or auto reset); uncommitted in-flight work from before a
        rebalance is redelivered — at-least-once."""
        self._fetch_pos.clear()
        self._committed.clear()
        self._outstanding = {p: set() for p in self._assignment}
        self._next_after_delivered = {}
        if not self._assignment:
            return
        committed = await self._client.offset_fetch(
            self._coordinator, self._group,
            [(self._topic, p) for p in self._assignment],
            conn=self._coord_conn,
        )
        for partition in self._assignment:
            offset = committed.get((self._topic, partition), -1)
            if offset < 0:
                offset = await self._client.list_offset(
                    self._topic, partition, self._auto_offset_reset
                )
            self._fetch_pos[partition] = offset
            self._committed[partition] = offset
            self._next_after_delivered[partition] = offset

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self._heartbeat_interval)
            try:
                # the lock covers the heartbeat AND any rejoin it
                # triggers: membership state (generation, member id,
                # coordinator connection) is only ever read or mutated
                # under the lock, so a heartbeat can't carry a stale
                # generation from a half-finished join and a rejoin can't
                # close the coordinator connection under an in-flight
                # commit (read()/commit() hold the same lock)
                async with self._membership_lock:
                    code = await self._client.heartbeat(
                        self._coordinator, self._group, self._generation,
                        self._member_id, conn=self._coord_conn,
                    )
                    if code in (
                        proto.REBALANCE_IN_PROGRESS,
                        proto.ILLEGAL_GENERATION,
                        proto.UNKNOWN_MEMBER_ID, proto.NOT_COORDINATOR,
                    ):
                        # rejoin NOW (not at the next poll): other
                        # members' rebalance windows wait for this
                        # member, and the owner may not be polling yet
                        if self._member_id:
                            self._generation = -1
                        await self._join()
                        self._rejoin_needed = False
            except Exception:  # noqa: BLE001 — transient; retry next beat
                continue

    # -- data ------------------------------------------------------------ #
    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        if not self._started:
            await self.start()
        # the WHOLE poll body runs under the membership lock: the
        # heartbeat task's rejoin can then only interleave BETWEEN
        # polls, never against an in-flight fetch whose positions a
        # _reset_positions() would invalidate
        async with self._membership_lock:
            if self._rejoin_needed:
                if self._member_id:
                    self._generation = -1
                await self._join()
            if not self._assignment:
                pause = timeout
            else:
                pause = 0.0
                out: List[Record] = []
                # ONE fetch covering every assigned partition: idle
                # partitions share a single long-poll instead of
                # serializing P timeouts
                results = await self._client.fetch_multi(
                    self._topic,
                    {p: self._fetch_pos[p] for p in self._assignment},
                    max_wait_ms=int(timeout * 1000),
                )
                # rotate the partition order so no partition starves
                # when max_records truncates the batch
                order = (
                    self._assignment[self._fetch_cursor:]
                    + self._assignment[:self._fetch_cursor]
                )
                self._fetch_cursor = (
                    self._fetch_cursor + 1
                ) % len(self._assignment)
                for partition in order:
                    records, _hw = results.get(partition, ([], -1))
                    for kafka_record in records:
                        if kafka_record.offset < self._fetch_pos[partition]:
                            continue  # batch replay below requested offset
                        if len(out) >= max_records:
                            break
                        view = decode_record(kafka_record, self._topic)
                        view = _dataclasses.replace(
                            view, partition=partition
                        )
                        view = await _maybe_avro(
                            self._registry, kafka_record, view
                        )
                        out.append(view)
                        self._fetch_pos[partition] = kafka_record.offset + 1
                        self._outstanding.setdefault(partition, set()).add(
                            kafka_record.offset
                        )
                        self._next_after_delivered[partition] = (
                            kafka_record.offset + 1
                        )
                self._delivered += len(out)
                return out
        # empty assignment: idle OUTSIDE the lock so heartbeats flow
        await asyncio.sleep(pause)
        return []

    async def commit(self, records: List[Record]) -> None:
        """Out-of-order acks allowed; durable offset = contiguous prefix
        (KafkaConsumerWrapper.java:52-230 semantics). The RPC itself is
        coalesced onto a short timer."""
        async with self._membership_lock:
            await self._commit_locked(records, self._commit_dirty)
            await self._flush_commits_locked()

    async def _flush_commits_locked(self, force: bool = False) -> None:
        import time as _time

        if not self._commit_dirty:
            return
        now = _time.monotonic()
        if not force and now - self._last_commit_flush < self._commit_interval:
            return
        if self._generation < 0:
            return
        dirty, self._commit_dirty = self._commit_dirty, {}
        self._last_commit_flush = now
        await self._client.offset_commit(
            self._coordinator, self._group, self._generation,
            self._member_id, dirty, conn=self._coord_conn,
        )

    async def _commit_locked(self, records, to_commit) -> None:
        for record in records:
            if not isinstance(record, KafkaRecordView):
                raise ValueError(
                    f"cannot commit a non-kafka record: {record!r}"
                )
            if record.partition not in self._outstanding:
                # partition reassigned away mid-flight: the new owner's
                # watermark is authoritative; committing here would
                # regress the group offset
                logger.info(
                    "dropping stale ack for %s/%d (not assigned)",
                    self._topic, record.partition,
                )
                continue
            outstanding = self._outstanding[record.partition]
            outstanding.discard(record.offset)
            watermark = (
                min(outstanding)
                if outstanding
                else self._next_after_delivered.get(record.partition, 0)
            )
            if watermark > self._committed.get(record.partition, -1):
                self._committed[record.partition] = watermark
                to_commit[(self._topic, record.partition)] = watermark
        # RPC handled by _flush_commits_locked (coalesced)

    def committed_offsets(self) -> Dict[int, int]:
        return dict(self._committed)

    async def close(self) -> None:
        async with self._membership_lock:
            try:
                await self._flush_commits_locked(force=True)
            except Exception:  # noqa: BLE001 — at-least-once: safe
                pass
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except asyncio.CancelledError:
                pass
            self._heartbeat_task = None
        if self._member_id and self._coordinator >= 0:
            await self._client.leave_group(
                self._coordinator, self._group, self._member_id,
                conn=self._coord_conn,
            )
        if self._coord_conn is not None:
            await self._coord_conn.close()
            self._coord_conn = None
        self._started = False

    def total_out(self) -> int:
        return self._delivered


# ---------------------------------------------------------------------- #
# reader (group-less tail)
# ---------------------------------------------------------------------- #
class KafkaTopicReader(TopicReader):
    def __init__(
        self, client: KafkaClient, topic: str, position: OffsetPosition,
        registry: Optional[avro_codec.SchemaRegistryClient] = None,
    ) -> None:
        self._client = client
        self._topic = topic
        self._position = position
        self._registry = registry
        self._offsets: Dict[int, int] = {}

    async def start(self) -> None:
        timestamp = (
            EARLIEST if self._position == OffsetPosition.EARLIEST else LATEST
        )
        for partition in await self._client.partitions_for(self._topic):
            self._offsets[partition] = await self._client.list_offset(
                self._topic, partition, timestamp
            )

    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        if not self._offsets:
            await self.start()
        out: List[Record] = []
        results = await self._client.fetch_multi(
            self._topic, dict(self._offsets),
            max_wait_ms=int(timeout * 1000),
        )
        for partition, (records, _hw) in results.items():
            for kafka_record in records:
                if kafka_record.offset < self._offsets[partition]:
                    continue
                if len(out) >= max_records:
                    return out
                view = decode_record(kafka_record, self._topic)
                view = _dataclasses.replace(view, partition=partition)
                view = await _maybe_avro(
                    self._registry, kafka_record, view
                )
                out.append(view)
                self._offsets[partition] = kafka_record.offset + 1
        return out


# ---------------------------------------------------------------------- #
# admin + runtime
# ---------------------------------------------------------------------- #
class KafkaTopicAdmin(TopicAdmin):
    def __init__(self, client: KafkaClient, replication: int = 1) -> None:
        self._client = client
        self._replication = replication

    async def create_topic(self, spec: TopicSpec) -> None:
        await self._client.create_topic(
            spec.name, max(1, spec.partitions), self._replication
        )

    async def delete_topic(self, name: str) -> None:
        await self._client.delete_topic(name)


class KafkaTopicConnectionsRuntime(TopicConnectionsRuntime):
    """``streamingCluster: {type: kafka, configuration: {bootstrapServers:
    host:port, ...}}`` (the reference accepts ``admin.bootstrap.servers``
    too — both spellings are honored here)."""

    def __init__(self, configuration: Optional[Dict[str, Any]] = None) -> None:
        configuration = configuration or {}
        admin = configuration.get("admin") or {}
        bootstrap = (
            configuration.get("bootstrapServers")
            or configuration.get("bootstrap_servers")
            or configuration.get("bootstrap.servers")
            or admin.get("bootstrap.servers")
            or admin.get("bootstrapServers")
            or "127.0.0.1:9092"
        )
        self.configuration = configuration
        self._client = KafkaClient(
            bootstrap,
            client_id=configuration.get("clientId", "langstream-tpu"),
            # ApiVersions handshake on every new connection (KIP-896
            # guard); `verifyApiVersions: false` opts out for brokers
            # that firewall the API
            verify_versions=bool(
                configuration.get("verifyApiVersions", True)
            ),
        )
        self._replication = int(configuration.get("replicationFactor", 1))
        registry_url = (
            configuration.get("schemaRegistryUrl")
            or configuration.get("schema.registry.url")
        )
        # foreign Confluent-Avro records decode into plain dict values
        # (the reference's schema-registry deserializer path)
        self._registry = (
            avro_codec.SchemaRegistryClient(registry_url)
            if registry_url else None
        )

    def create_consumer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicConsumer:
        return KafkaTopicConsumer(
            self._client,
            config["topic"],
            config.get("group") or f"langstream-{agent_id}",
            session_timeout_ms=int(
                self.configuration.get("sessionTimeoutMs", 10000)
            ),
            auto_offset_reset=(
                LATEST
                if self.configuration.get("autoOffsetReset") == "latest"
                else EARLIEST
            ),
            registry=self._registry,
        )

    def create_producer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicProducer:
        value_schema = None
        schema_config = config.get("schema") or {}
        schema_type = str(schema_config.get("type", "")).lower()
        if (
            self._registry is not None
            and schema_type == "avro"
            and schema_config.get("schema")
        ):
            value_schema = avro_codec.parse_schema(schema_config["schema"])
        producer = KafkaTopicProducer(
            self._client, config["topic"],
            value_schema=value_schema, registry=self._registry,
        )
        if schema_type in ("string", "json", "bytes"):
            producer._plain_type = schema_type  # noqa: SLF001
        return producer

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        return KafkaTopicReader(
            self._client, config["topic"], initial_position,
            registry=self._registry,
        )

    def create_admin(self) -> TopicAdmin:
        return KafkaTopicAdmin(self._client, self._replication)

    async def close(self) -> None:
        if self._registry is not None:
            await self._registry.close()
        await self._client.close()
