"""Kafka wire protocol: primitives, message codecs, record batches v2.

Implemented from the public protocol specification (kafka.apache.org/
protocol). Non-flexible (pre-KIP-482) API versions are used throughout so
no tagged-field plumbing is needed; every schema below is pinned to one
version:

=================  =====  ===
API                key    ver
=================  =====  ===
Produce            0      3
Fetch              1      4
ListOffsets        2      1
Metadata           3      1
OffsetCommit       8      2
OffsetFetch        9      1
FindCoordinator    10     0
JoinGroup          11     1
Heartbeat          12     0
LeaveGroup         13     0
SyncGroup          14     0
ApiVersions        18     0
CreateTopics       19     0
DeleteTopics       20     0
=================  =====  ===

Record batches are magic-v2 (the only format v3+ Produce accepts):
varint-encoded records guarded by a CRC32C over the batch payload.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------- #
# api keys + error codes
# ---------------------------------------------------------------------- #
PRODUCE, FETCH, LIST_OFFSETS, METADATA = 0, 1, 2, 3
OFFSET_COMMIT, OFFSET_FETCH, FIND_COORDINATOR = 8, 9, 10
JOIN_GROUP, HEARTBEAT, LEAVE_GROUP, SYNC_GROUP = 11, 12, 13, 14
API_VERSIONS, CREATE_TOPICS, DELETE_TOPICS = 18, 19, 20

NONE = 0
UNKNOWN_TOPIC_OR_PARTITION = 3
NOT_LEADER_FOR_PARTITION = 6
COORDINATOR_NOT_AVAILABLE = 15
NOT_COORDINATOR = 16
ILLEGAL_GENERATION = 22
UNKNOWN_MEMBER_ID = 25
REBALANCE_IN_PROGRESS = 27
TOPIC_ALREADY_EXISTS = 36
MEMBER_ID_REQUIRED = 79

RETRIABLE = {
    UNKNOWN_TOPIC_OR_PARTITION, NOT_LEADER_FOR_PARTITION,
    COORDINATOR_NOT_AVAILABLE, NOT_COORDINATOR, REBALANCE_IN_PROGRESS,
}

# every API version this client sends, in one place. The connection
# handshake verifies each against the broker's advertised [min, max]
# (ApiVersions), so "broker too new" (KIP-896: Kafka 4.0 removed
# pre-2.1 protocol versions) or "broker too old" fails at connect with
# a precise message instead of a mid-traffic decode error. ApiVersions
# itself is the bootstrap: brokers answer it at v0 regardless of their
# floor, exactly so old clients learn they are unsupported.
PINNED_VERSIONS: Dict[int, int] = {
    PRODUCE: 3, FETCH: 4, LIST_OFFSETS: 1, METADATA: 1,
    OFFSET_COMMIT: 2, OFFSET_FETCH: 1, FIND_COORDINATOR: 0,
    JOIN_GROUP: 1, HEARTBEAT: 0, LEAVE_GROUP: 0, SYNC_GROUP: 0,
    API_VERSIONS: 0, CREATE_TOPICS: 0, DELETE_TOPICS: 0,
}

API_NAMES: Dict[int, str] = {
    PRODUCE: "Produce", FETCH: "Fetch", LIST_OFFSETS: "ListOffsets",
    METADATA: "Metadata", OFFSET_COMMIT: "OffsetCommit",
    OFFSET_FETCH: "OffsetFetch", FIND_COORDINATOR: "FindCoordinator",
    JOIN_GROUP: "JoinGroup", HEARTBEAT: "Heartbeat",
    LEAVE_GROUP: "LeaveGroup", SYNC_GROUP: "SyncGroup",
    API_VERSIONS: "ApiVersions", CREATE_TOPICS: "CreateTopics",
    DELETE_TOPICS: "DeleteTopics",
}


def decode_api_versions(reader: "Reader") -> Dict[int, Tuple[int, int]]:
    """ApiVersions v0 response body → {api_key: (min, max)}. The
    leading error_code is returned under key -1 for the caller."""
    error_code = reader.int16()
    out: Dict[int, Tuple[int, int]] = {-1: (error_code, error_code)}
    for _ in range(reader.int32()):
        api_key = reader.int16()
        out[api_key] = (reader.int16(), reader.int16())
    return out


def unsupported_pinned_apis(
    advertised: Dict[int, Tuple[int, int]],
) -> List[str]:
    """Which pinned (api, version) pairs the broker does not serve."""
    problems: List[str] = []
    for api_key, version in sorted(PINNED_VERSIONS.items()):
        if api_key == API_VERSIONS:
            continue  # the handshake itself already round-tripped
        if api_key not in advertised:
            problems.append(f"{API_NAMES[api_key]} (not offered)")
            continue
        low, high = advertised[api_key]
        if not low <= version <= high:
            problems.append(
                f"{API_NAMES[api_key]} v{version} (broker serves "
                f"v{low}..v{high})"
            )
    return problems


class KafkaProtocolError(RuntimeError):
    def __init__(self, code: int, context: str = "") -> None:
        super().__init__(f"kafka error {code} {context}".strip())
        self.code = code


# ---------------------------------------------------------------------- #
# crc32c (Castagnoli, reflected poly 0x82F63B78) — required by batch v2
# ---------------------------------------------------------------------- #
def _crc32c_table() -> List[int]:
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC_TABLE = _crc32c_table()


def _crc32c_python(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for byte in data:
        crc = _CRC_TABLE[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _resolve_crc32c():
    """Prefer the native slice-by-8 implementation (~400× the Python
    table loop — the CRC covers every produced/validated batch payload);
    fall back to pure Python when the toolchain is unavailable."""
    try:
        from langstream_tpu.native import load_kafkacodec

        lib = load_kafkacodec()
    except Exception:  # noqa: BLE001 — any native failure → fallback
        lib = None
    if lib is None:
        return _crc32c_python

    def native(data: bytes, crc: int = 0) -> int:
        return lib.ls_crc32c(data, len(data), crc)

    return native


crc32c = _resolve_crc32c()


# ---------------------------------------------------------------------- #
# primitive codecs
# ---------------------------------------------------------------------- #
class Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def raw(self, data: bytes) -> "Writer":
        self._parts.append(data)
        return self

    def int8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def int16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def int32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def int64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def uint32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def boolean(self, v: bool) -> "Writer":
        return self.int8(1 if v else 0)

    def string(self, v: Optional[str]) -> "Writer":
        if v is None:
            return self.int16(-1)
        data = v.encode("utf-8")
        return self.int16(len(data)).raw(data)

    def bytes_(self, v: Optional[bytes]) -> "Writer":
        if v is None:
            return self.int32(-1)
        return self.int32(len(v)).raw(v)

    def varint(self, v: int) -> "Writer":
        """Zigzag-encoded signed varint."""
        return self.uvarint((v << 1) ^ (v >> 31))

    def varlong(self, v: int) -> "Writer":
        return self.uvarint((v << 1) ^ (v >> 63))

    def uvarint(self, v: int) -> "Writer":
        out = bytearray()
        while True:
            byte = v & 0x7F
            v >>= 7
            if v:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
        return self.raw(bytes(out))

    def array(self, items: List[Any], encode) -> "Writer":
        self.int32(len(items))
        for item in items:
            encode(self, item)
        return self

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise EOFError(f"need {n} bytes at {self.pos}/{len(self.data)}")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def boolean(self) -> bool:
        return self.int8() != 0

    def string(self) -> Optional[str]:
        n = self.int16()
        return None if n < 0 else self._take(n).decode("utf-8")

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        return None if n < 0 else self._take(n)

    def uvarint(self) -> int:
        shift = value = 0
        while True:
            byte = self._take(1)[0]
            value |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return value
            shift += 7

    def varint(self) -> int:
        value = self.uvarint()
        return (value >> 1) ^ -(value & 1)

    varlong = varint

    def array(self, decode) -> List[Any]:
        n = self.int32()
        return [decode(self) for _ in range(max(0, n))]

    def remaining(self) -> int:
        return len(self.data) - self.pos


# ---------------------------------------------------------------------- #
# request framing
# ---------------------------------------------------------------------- #
def encode_request(
    api_key: int, api_version: int, correlation_id: int,
    client_id: Optional[str], body: bytes,
) -> bytes:
    header = (
        Writer().int16(api_key).int16(api_version).int32(correlation_id)
        .string(client_id).build()
    )
    payload = header + body
    return struct.pack(">i", len(payload)) + payload


# ---------------------------------------------------------------------- #
# record batches (magic v2)
# ---------------------------------------------------------------------- #
@dataclasses.dataclass
class KafkaRecord:
    offset: int
    timestamp: int
    key: Optional[bytes]
    value: Optional[bytes]
    headers: List[Tuple[str, Optional[bytes]]]


def encode_record_batch(
    records: List[Tuple[Optional[bytes], Optional[bytes],
                        List[Tuple[str, Optional[bytes]]], int]],
    base_offset: int = 0,
) -> bytes:
    """records: [(key, value, headers, timestamp_ms)] → one batch."""
    if not records:
        return b""
    base_timestamp = records[0][3]
    max_timestamp = max(r[3] for r in records)
    body = Writer()
    for i, (key, value, headers, timestamp) in enumerate(records):
        record = Writer()
        record.int8(0)  # attributes
        record.varlong(timestamp - base_timestamp)
        record.varint(i)  # offset delta
        if key is None:
            record.varint(-1)
        else:
            record.varint(len(key)).raw(key)
        if value is None:
            record.varint(-1)
        else:
            record.varint(len(value)).raw(value)
        record.varint(len(headers))
        for name, hvalue in headers:
            name_bytes = name.encode("utf-8")
            record.varint(len(name_bytes)).raw(name_bytes)
            if hvalue is None:
                record.varint(-1)
            else:
                record.varint(len(hvalue)).raw(hvalue)
        encoded = record.build()
        body.varint(len(encoded)).raw(encoded)

    # the crc covers attributes..records
    after_crc = (
        Writer()
        .int16(0)                      # attributes (no compression)
        .int32(len(records) - 1)       # last offset delta
        .int64(base_timestamp)
        .int64(max_timestamp)
        .int64(-1)                     # producer id
        .int16(-1)                     # producer epoch
        .int32(-1)                     # base sequence
        .int32(len(records))
        .raw(body.build())
        .build()
    )
    crc = crc32c(after_crc)
    batch_tail = (
        Writer()
        .int32(-1)                     # partition leader epoch
        .int8(2)                       # magic
        .uint32(crc)
        .raw(after_crc)
        .build()
    )
    return (
        Writer()
        .int64(base_offset)
        .int32(len(batch_tail))
        .raw(batch_tail)
        .build()
    )


def decode_record_batches(data: bytes) -> List[KafkaRecord]:
    """Parse a record set (possibly several concatenated batches; a
    truncated trailing batch — normal in Fetch responses — is skipped)."""
    out: List[KafkaRecord] = []
    reader = Reader(data)
    while reader.remaining() >= 12:
        base_offset = reader.int64()
        batch_length = reader.int32()
        if reader.remaining() < batch_length:
            break  # truncated tail
        batch = Reader(reader._take(batch_length))
        batch.int32()  # partition leader epoch
        magic = batch.int8()
        if magic != 2:
            continue  # legacy message sets unsupported (pre-0.11 brokers)
        batch.uint32()  # crc (trusted: TCP + broker already validated)
        attributes = batch.int16()
        if attributes & 0x20:
            # control batch (transaction commit/abort markers): consumes
            # offsets but carries no application records
            continue
        if attributes & 0x07:
            raise KafkaProtocolError(
                NONE, "compressed batches not supported (set "
                "compression.type=none / produce uncompressed)"
            )
        batch.int32()  # last offset delta
        base_timestamp = batch.int64()
        batch.int64()  # max timestamp
        batch.int64()  # producer id
        batch.int16()  # producer epoch
        batch.int32()  # base sequence
        count = batch.int32()
        for _ in range(count):
            length = batch.varint()
            record = Reader(batch._take(length))
            record.int8()  # attributes
            ts_delta = record.varlong()
            offset_delta = record.varint()
            key_len = record.varint()
            key = record._take(key_len) if key_len >= 0 else None
            value_len = record.varint()
            value = record._take(value_len) if value_len >= 0 else None
            headers: List[Tuple[str, Optional[bytes]]] = []
            for _h in range(record.varint()):
                name_len = record.varint()
                name = record._take(name_len).decode("utf-8")
                hlen = record.varint()
                hvalue = record._take(hlen) if hlen >= 0 else None
                headers.append((name, hvalue))
            out.append(KafkaRecord(
                offset=base_offset + offset_delta,
                timestamp=base_timestamp + ts_delta,
                key=key, value=value, headers=headers,
            ))
    return out


# ---------------------------------------------------------------------- #
# consumer-group protocol blobs (protocol type "consumer", strategy range)
# ---------------------------------------------------------------------- #
def encode_subscription(topics: List[str]) -> bytes:
    writer = Writer().int16(0)
    writer.array(sorted(topics), lambda w, t: w.string(t))
    writer.bytes_(b"")
    return writer.build()


def decode_subscription(data: bytes) -> List[str]:
    reader = Reader(data)
    reader.int16()  # version
    return reader.array(lambda r: r.string())


def encode_assignment(assignment: Dict[str, List[int]]) -> bytes:
    writer = Writer().int16(0)
    writer.array(
        sorted(assignment.items()),
        lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, p: w2.int32(p)),
        ),
    )
    writer.bytes_(b"")
    return writer.build()


def decode_assignment(data: bytes) -> Dict[str, List[int]]:
    if not data:
        return {}
    reader = Reader(data)
    reader.int16()
    out: Dict[str, List[int]] = {}
    for _ in range(reader.int32()):
        topic = reader.string()
        out[topic] = reader.array(lambda r: r.int32())
    return out


def range_assign(
    members: List[Tuple[str, List[str]]],
    partitions_by_topic: Dict[str, int],
) -> Dict[str, Dict[str, List[int]]]:
    """The leader-side range assignor: contiguous partition spans per
    member, per topic (Kafka's default RangeAssignor semantics)."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m, _ in members}
    topics: Dict[str, List[str]] = {}
    for member_id, subscribed in members:
        for topic in subscribed:
            topics.setdefault(topic, []).append(member_id)
    for topic, member_ids in topics.items():
        member_ids.sort()
        count = partitions_by_topic.get(topic, 0)
        n = len(member_ids)
        base, extra = divmod(count, n)
        start = 0
        for i, member_id in enumerate(member_ids):
            take = base + (1 if i < extra else 0)
            if take:
                out[member_id][topic] = list(range(start, start + take))
            start += take
    return out
