"""Kafka topic runtime: a from-scratch wire-protocol client (and a
Kafka-protocol facade for the in-process broker) implementing the Topic
SPI, so applications written against this framework run unchanged on an
existing Kafka cluster (``streamingCluster.type: kafka``).

Reference: ``langstream-kafka-runtime/src/main/java/ai/langstream/kafka/
runner/KafkaTopicConnectionsRuntime.java:53`` (SPI wiring) and
``KafkaConsumerWrapper.java:52-230`` (out-of-order ack bookkeeping with a
contiguous commit watermark — reimplemented here client-side, the same
semantics the in-memory broker enforces server-side).

No kafka client library exists in this image, so the protocol layer is
implemented directly (framing, record batches v2 with CRC32C, consumer
groups); see ``protocol.py``.
"""

from langstream_tpu.topics.kafka.runtime import KafkaTopicConnectionsRuntime

__all__ = ["KafkaTopicConnectionsRuntime"]
