"""Asyncio Kafka client core: connections, metadata, API calls.

One :class:`KafkaClient` per topic runtime; it owns one
:class:`KafkaConnection` per broker node and the cluster metadata. All
request/response codecs live here, pinned to the versions documented in
``protocol.py``.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from langstream_tpu.topics.kafka import protocol as proto
from langstream_tpu.topics.kafka.protocol import (
    KafkaProtocolError,
    Reader,
    Writer,
)

logger = logging.getLogger(__name__)


class KafkaVersionError(KafkaProtocolError):
    """The broker does not serve the protocol versions this client
    pins (broker older than ~0.11, or newer than the KIP-896 floor —
    Kafka 4.0 removed pre-2.1 request versions). Raised at connect by
    the ApiVersions handshake, never mid-traffic."""

    def __init__(self, broker: str, problems: List[str]) -> None:
        super().__init__(
            proto.NONE,
            f"broker {broker} does not support pinned protocol "
            f"versions: {', '.join(problems)}. Supported broker range: "
            "Apache Kafka 0.11 .. 3.x (KIP-896 removed these versions "
            "in 4.0).",
        )
        self.problems = problems


class KafkaConnection:
    """One framed request/response socket. Kafka guarantees in-order
    responses per connection, so a FIFO of pending futures suffices.

    ``connect`` performs the ApiVersions handshake (v0 — the bootstrap
    version every broker answers) and verifies each pinned API version
    against the broker's advertised ranges, so version skew fails
    loudly at connect (reference relies on the Apache client's
    identical NetworkClient handshake)."""

    def __init__(
        self,
        host: str,
        port: int,
        client_id: str,
        verify_versions: bool = True,
    ) -> None:
        self.host, self.port = host, port
        self.client_id = client_id
        self.verify_versions = verify_versions
        self.api_versions: Optional[Dict[int, Tuple[int, int]]] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._correlation = itertools.count(1)
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        if self._writer is not None:
            return
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        if self.verify_versions and self.api_versions is None:
            try:
                await self._version_handshake()
            except BaseException:
                await self.close()
                raise

    async def _version_handshake(self, timeout: float = 30.0) -> None:
        """ApiVersions v0 round trip directly on the fresh socket (the
        caller may already hold the request lock)."""
        correlation_id = next(self._correlation)
        frame = proto.encode_request(
            proto.API_VERSIONS, 0, correlation_id, self.client_id, b""
        )
        self._writer.write(frame)
        await self._writer.drain()
        size_bytes = await asyncio.wait_for(
            self._reader.readexactly(4), timeout
        )
        payload = await asyncio.wait_for(
            self._reader.readexactly(int.from_bytes(size_bytes, "big")),
            timeout,
        )
        reader = Reader(payload)
        got = reader.int32()
        if got != correlation_id:
            raise KafkaProtocolError(
                proto.NONE,
                f"ApiVersions correlation mismatch {got} != {correlation_id}",
            )
        advertised = proto.decode_api_versions(reader)
        error_code = advertised.pop(-1)[0]
        if error_code != proto.NONE:
            raise KafkaProtocolError(
                error_code, "ApiVersions request rejected"
            )
        self.api_versions = advertised
        problems = proto.unsupported_pinned_apis(advertised)
        if problems:
            raise KafkaVersionError(f"{self.host}:{self.port}", problems)

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None
            self._reader = None
            # re-handshake on reconnect: the broker behind this address
            # may have been upgraded while we were away
            self.api_versions = None

    async def call(
        self, api_key: int, api_version: int, body: bytes,
        timeout: float = 30.0,
    ) -> Reader:
        async with self._lock:  # serialize request/response pairs
            await self.connect()
            correlation_id = next(self._correlation)
            frame = proto.encode_request(
                api_key, api_version, correlation_id, self.client_id, body
            )
            try:
                self._writer.write(frame)
                await self._writer.drain()
                size_bytes = await asyncio.wait_for(
                    self._reader.readexactly(4), timeout
                )
                size = int.from_bytes(size_bytes, "big")
                payload = await asyncio.wait_for(
                    self._reader.readexactly(size), timeout
                )
            except (
                asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, OSError,
            ):
                # a timed-out request leaves its response in flight; the
                # connection is desynced — drop it so the next call
                # reconnects instead of reading the stale frame
                await self.close()
                raise
            reader = Reader(payload)
            got = reader.int32()
            if got != correlation_id:
                await self.close()
                raise KafkaProtocolError(
                    proto.NONE,
                    f"correlation mismatch {got} != {correlation_id}",
                )
            return reader


class BrokerInfo:
    __slots__ = ("node_id", "host", "port")

    def __init__(self, node_id: int, host: str, port: int) -> None:
        self.node_id, self.host, self.port = node_id, host, port


class KafkaClient:
    def __init__(
        self,
        bootstrap_servers: str,
        *,
        client_id: str = "langstream-tpu",
        verify_versions: bool = True,
    ) -> None:
        self.verify_versions = verify_versions
        self.bootstrap: List[Tuple[str, int]] = []
        for part in bootstrap_servers.split(","):
            part = part.strip()
            if ":" in part:
                host, _, port = part.rpartition(":")
                self.bootstrap.append((host or "127.0.0.1", int(port)))
            else:
                self.bootstrap.append((part, 9092))  # Kafka default port
        self.client_id = client_id
        self.brokers: Dict[int, BrokerInfo] = {}
        self.controller_id: int = -1
        # topic -> partition -> leader node id
        self.leaders: Dict[str, Dict[int, int]] = {}
        self._connections: Dict[Any, KafkaConnection] = {}

    # -- connections ---------------------------------------------------- #
    def _bootstrap_connection(self) -> KafkaConnection:
        key = ("bootstrap", *self.bootstrap[0])
        if key not in self._connections:
            host, port = self.bootstrap[0]
            self._connections[key] = KafkaConnection(
                host, port, self.client_id,
                verify_versions=self.verify_versions,
            )
        return self._connections[key]

    def node_connection(self, node_id: int) -> KafkaConnection:
        broker = self.brokers[node_id]
        if node_id not in self._connections:
            self._connections[node_id] = KafkaConnection(
                broker.host, broker.port, self.client_id,
                verify_versions=self.verify_versions,
            )
        return self._connections[node_id]

    def dedicated_connection(self, node_id: int) -> KafkaConnection:
        """A private (uncached) connection. Each consumer keeps its own
        coordinator channel so one member's join (which blocks inside the
        broker's rebalance barrier) never serializes another member's —
        the same one-socket-per-consumer layout real clients use."""
        broker = self.brokers[node_id]
        return KafkaConnection(
            broker.host, broker.port, self.client_id,
            verify_versions=self.verify_versions,
        )

    async def close(self) -> None:
        for connection in self._connections.values():
            await connection.close()
        self._connections.clear()

    # -- metadata (v1) --------------------------------------------------- #
    async def refresh_metadata(self, topics: Optional[List[str]] = None) -> None:
        body = Writer()
        if topics is None:
            body.int32(-1)
        else:
            body.array(topics, lambda w, t: w.string(t))
        reader = await self._bootstrap_connection().call(
            proto.METADATA, 1, body.build()
        )
        brokers = {}
        for _ in range(reader.int32()):
            node_id = reader.int32()
            host = reader.string()
            port = reader.int32()
            reader.string()  # rack
            brokers[node_id] = BrokerInfo(node_id, host, port)
        self.brokers = brokers
        self.controller_id = reader.int32()
        for _ in range(reader.int32()):
            error = reader.int16()
            name = reader.string()
            reader.boolean()  # is_internal
            partitions: Dict[int, int] = {}
            for _p in range(reader.int32()):
                reader.int16()  # partition error
                partition = reader.int32()
                leader = reader.int32()
                reader.array(lambda r: r.int32())  # replicas
                reader.array(lambda r: r.int32())  # isr
                partitions[partition] = leader
            if error == proto.NONE:
                self.leaders[name] = partitions

    async def leader_for(self, topic: str, partition: int) -> int:
        for _ in range(5):
            leader = self.leaders.get(topic, {}).get(partition, -1)
            if leader >= 0 and leader in self.brokers:
                return leader
            await self.refresh_metadata([topic])
            await asyncio.sleep(0.1)
        raise KafkaProtocolError(
            proto.NOT_LEADER_FOR_PARTITION, f"{topic}/{partition}"
        )

    async def partitions_for(self, topic: str) -> List[int]:
        if topic not in self.leaders:
            await self.refresh_metadata([topic])
        return sorted(self.leaders.get(topic, {}))

    # -- produce (v3) ----------------------------------------------------- #
    async def produce(
        self, topic: str, partition: int, record_set: bytes,
        acks: int = -1, timeout_ms: int = 30000,
    ) -> int:
        """Returns the base offset assigned by the broker."""
        for attempt in range(5):
            leader = await self.leader_for(topic, partition)
            body = (
                Writer()
                .string(None)        # transactional id
                .int16(acks)
                .int32(timeout_ms)
                .array([None], lambda w, _: (
                    w.string(topic),
                    w.array([None], lambda w2, _2: (
                        w2.int32(partition),
                        w2.bytes_(record_set),
                    )),
                ))
                .build()
            )
            reader = await self.node_connection(leader).call(
                proto.PRODUCE, 3, body
            )
            error = base_offset = None
            for _ in range(reader.int32()):
                reader.string()
                for _p in range(reader.int32()):
                    reader.int32()
                    error = reader.int16()
                    base_offset = reader.int64()
                    reader.int64()  # log append time
            reader.int32()  # throttle
            if error == proto.NONE:
                return base_offset
            if error in proto.RETRIABLE and attempt < 4:
                await self.refresh_metadata([topic])
                await asyncio.sleep(0.1 * (attempt + 1))
                continue
            raise KafkaProtocolError(error, f"produce {topic}/{partition}")
        raise KafkaProtocolError(proto.NONE, "produce retries exhausted")

    # -- fetch (v4) -------------------------------------------------------- #
    async def fetch(
        self, topic: str, partition: int, offset: int,
        max_wait_ms: int = 100, min_bytes: int = 1,
        max_bytes: int = 4 * 1024 * 1024,
    ) -> Tuple[List[proto.KafkaRecord], int]:
        """Single-partition fetch → (records, high_watermark)."""
        result = await self.fetch_multi(
            topic, {partition: offset}, max_wait_ms=max_wait_ms,
            min_bytes=min_bytes, max_bytes=max_bytes,
        )
        return result.get(partition, ([], -1))

    async def fetch_multi(
        self, topic: str, offsets: Dict[int, int],
        max_wait_ms: int = 100, min_bytes: int = 1,
        max_bytes: int = 4 * 1024 * 1024,
    ) -> Dict[int, Tuple[List[proto.KafkaRecord], int]]:
        """Fetch MANY partitions in one request per leader (idle-partition
        long-polls overlap instead of serializing — a consumer assigned P
        partitions pays one wait, not P). Returns
        {partition: (records, high_watermark)}."""
        by_leader: Dict[int, List[int]] = {}
        for partition in offsets:
            leader = await self.leader_for(topic, partition)
            by_leader.setdefault(leader, []).append(partition)

        out: Dict[int, Tuple[List[proto.KafkaRecord], int]] = {}

        async def fetch_from(leader: int, partitions: List[int]) -> None:
            body = (
                Writer()
                .int32(-1)           # replica id
                .int32(max_wait_ms)
                .int32(min_bytes)
                .int32(max_bytes)
                .int8(0)             # isolation level: read uncommitted
                .array([None], lambda w, _: (
                    w.string(topic),
                    w.array(partitions, lambda w2, p: (
                        w2.int32(p),
                        w2.int64(offsets[p]),
                        w2.int32(max_bytes),
                    )),
                ))
                .build()
            )
            reader = await self.node_connection(leader).call(
                proto.FETCH, 4, body,
                timeout=max(30.0, max_wait_ms / 1000 + 30),
            )
            reader.int32()  # throttle
            for _ in range(reader.int32()):
                reader.string()
                for _p in range(reader.int32()):
                    partition = reader.int32()
                    error = reader.int16()
                    high_watermark = reader.int64()
                    reader.int64()  # last stable offset
                    aborted = reader.int32()
                    for _a in range(max(0, aborted)):
                        reader.int64()
                        reader.int64()
                    record_set = reader.bytes_()
                    if error == proto.NONE:
                        out[partition] = (
                            proto.decode_record_batches(record_set or b""),
                            high_watermark,
                        )
                    elif error in proto.RETRIABLE:
                        await self.refresh_metadata([topic])
                        out[partition] = ([], high_watermark)
                    else:
                        raise KafkaProtocolError(
                            error, f"fetch {topic}/{partition}"
                        )

        for leader, partitions in by_leader.items():
            await fetch_from(leader, partitions)
        return out

    # -- list offsets (v1) -------------------------------------------------- #
    async def list_offset(
        self, topic: str, partition: int, timestamp: int
    ) -> int:
        """timestamp: -2 earliest, -1 latest → offset."""
        leader = await self.leader_for(topic, partition)
        body = (
            Writer()
            .int32(-1)
            .array([None], lambda w, _: (
                w.string(topic),
                w.array([None], lambda w2, _2: (
                    w2.int32(partition),
                    w2.int64(timestamp),
                )),
            ))
            .build()
        )
        reader = await self.node_connection(leader).call(
            proto.LIST_OFFSETS, 1, body
        )
        offset = -1
        for _ in range(reader.int32()):
            reader.string()
            for _p in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                reader.int64()  # timestamp
                offset = reader.int64()
                if error != proto.NONE:
                    raise KafkaProtocolError(
                        error, f"list_offsets {topic}/{partition}"
                    )
        return offset

    # -- group coordination ------------------------------------------------- #
    async def find_coordinator(self, group_id: str) -> int:
        for attempt in range(10):
            body = Writer().string(group_id).build()
            reader = await self._bootstrap_connection().call(
                proto.FIND_COORDINATOR, 0, body
            )
            error = reader.int16()
            node_id = reader.int32()
            host = reader.string()
            port = reader.int32()
            if error == proto.NONE:
                self.brokers.setdefault(
                    node_id, BrokerInfo(node_id, host, port)
                )
                return node_id
            if error == proto.COORDINATOR_NOT_AVAILABLE:
                await asyncio.sleep(0.2 * (attempt + 1))
                continue
            raise KafkaProtocolError(error, f"find_coordinator {group_id}")
        raise KafkaProtocolError(
            proto.COORDINATOR_NOT_AVAILABLE, group_id
        )

    async def join_group(
        self, coordinator: int, group_id: str, member_id: str,
        topics: List[str], session_timeout_ms: int = 10000,
        rebalance_timeout_ms: int = 60000,
        conn: Optional[KafkaConnection] = None,
    ) -> Dict[str, Any]:
        body = (
            Writer()
            .string(group_id)
            .int32(session_timeout_ms)
            .int32(rebalance_timeout_ms)
            .string(member_id)
            .string("consumer")
            .array([None], lambda w, _: (
                w.string("range"),
                w.bytes_(proto.encode_subscription(topics)),
            ))
            .build()
        )
        reader = await (conn or self.node_connection(coordinator)).call(
            proto.JOIN_GROUP, 1, body, timeout=rebalance_timeout_ms / 1000 + 30
        )
        error = reader.int16()
        generation = reader.int32()
        protocol_name = reader.string()
        leader = reader.string()
        assigned_member = reader.string()
        members = []
        for _ in range(reader.int32()):
            mid = reader.string()
            metadata = reader.bytes_()
            members.append((mid, proto.decode_subscription(metadata or b"")))
        if error != proto.NONE:
            failure = KafkaProtocolError(error, f"join_group {group_id}")
            # KIP-394: the broker assigns a member id on the rejected
            # first join; surface it so the retry can present it
            failure.member_id = assigned_member
            raise failure
        return {
            "generation": generation,
            "protocol": protocol_name,
            "leader": leader,
            "member_id": assigned_member,
            "members": members,
        }

    async def sync_group(
        self, coordinator: int, group_id: str, generation: int,
        member_id: str,
        assignments: Optional[Dict[str, Dict[str, List[int]]]] = None,
        conn: Optional[KafkaConnection] = None,
    ) -> Dict[str, List[int]]:
        writer = (
            Writer()
            .string(group_id)
            .int32(generation)
            .string(member_id)
        )
        items = sorted((assignments or {}).items())
        writer.array(items, lambda w, item: (
            w.string(item[0]),
            w.bytes_(proto.encode_assignment(item[1])),
        ))
        reader = await (conn or self.node_connection(coordinator)).call(
            proto.SYNC_GROUP, 0, writer.build(), timeout=90
        )
        error = reader.int16()
        assignment = reader.bytes_()
        if error != proto.NONE:
            raise KafkaProtocolError(error, f"sync_group {group_id}")
        return proto.decode_assignment(assignment or b"")

    async def heartbeat(
        self, coordinator: int, group_id: str, generation: int,
        member_id: str, conn: Optional[KafkaConnection] = None,
    ) -> int:
        body = (
            Writer().string(group_id).int32(generation).string(member_id)
            .build()
        )
        reader = await (conn or self.node_connection(coordinator)).call(
            proto.HEARTBEAT, 0, body
        )
        return reader.int16()

    async def leave_group(
        self, coordinator: int, group_id: str, member_id: str,
        conn: Optional[KafkaConnection] = None,
    ) -> None:
        body = Writer().string(group_id).string(member_id).build()
        try:
            await (conn or self.node_connection(coordinator)).call(
                proto.LEAVE_GROUP, 0, body, timeout=5
            )
        except Exception:  # noqa: BLE001 — best effort on shutdown
            pass

    async def offset_commit(
        self, coordinator: int, group_id: str, generation: int,
        member_id: str, offsets: Dict[Tuple[str, int], int],
        conn: Optional[KafkaConnection] = None,
    ) -> None:
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for (topic, partition), offset in offsets.items():
            by_topic.setdefault(topic, []).append((partition, offset))
        writer = (
            Writer()
            .string(group_id)
            .int32(generation)
            .string(member_id)
            .int64(-1)  # retention time: broker default
        )
        writer.array(sorted(by_topic.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, po: (
                w2.int32(po[0]),
                w2.int64(po[1]),
                w2.string(None),
            )),
        ))
        reader = await (conn or self.node_connection(coordinator)).call(
            proto.OFFSET_COMMIT, 2, writer.build()
        )
        for _ in range(reader.int32()):
            reader.string()
            for _p in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                if error != proto.NONE:
                    raise KafkaProtocolError(
                        error, f"offset_commit {group_id}"
                    )

    async def offset_fetch(
        self, coordinator: int, group_id: str,
        partitions: List[Tuple[str, int]],
        conn: Optional[KafkaConnection] = None,
    ) -> Dict[Tuple[str, int], int]:
        by_topic: Dict[str, List[int]] = {}
        for topic, partition in partitions:
            by_topic.setdefault(topic, []).append(partition)
        writer = Writer().string(group_id)
        writer.array(sorted(by_topic.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, p: w2.int32(p)),
        ))
        reader = await (conn or self.node_connection(coordinator)).call(
            proto.OFFSET_FETCH, 1, writer.build()
        )
        out: Dict[Tuple[str, int], int] = {}
        for _ in range(reader.int32()):
            topic = reader.string()
            for _p in range(reader.int32()):
                partition = reader.int32()
                offset = reader.int64()
                reader.string()  # metadata
                error = reader.int16()
                if error == proto.NONE:
                    out[(topic, partition)] = offset
        return out

    # -- topic admin ---------------------------------------------------------- #
    async def create_topic(
        self, name: str, partitions: int, replication: int = 1,
        timeout_ms: int = 30000,
    ) -> None:
        await self.refresh_metadata([])
        controller = (
            self.controller_id
            if self.controller_id in self.brokers
            else next(iter(self.brokers), -1)
        )
        connection = (
            self.node_connection(controller)
            if controller >= 0 else self._bootstrap_connection()
        )
        body = (
            Writer()
            .array([None], lambda w, _: (
                w.string(name),
                w.int32(partitions),
                w.int16(replication),
                w.int32(0),   # manual assignments: none
                w.int32(0),   # configs: none
            ))
            .int32(timeout_ms)
            .build()
        )
        reader = await connection.call(proto.CREATE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error not in (proto.NONE, proto.TOPIC_ALREADY_EXISTS):
                raise KafkaProtocolError(error, f"create_topic {name}")
        await self.refresh_metadata([name])

    async def delete_topic(self, name: str, timeout_ms: int = 30000) -> None:
        await self.refresh_metadata([])
        controller = (
            self.controller_id
            if self.controller_id in self.brokers
            else next(iter(self.brokers), -1)
        )
        connection = (
            self.node_connection(controller)
            if controller >= 0 else self._bootstrap_connection()
        )
        body = (
            Writer()
            .array([name], lambda w, t: w.string(t))
            .int32(timeout_ms)
            .build()
        )
        reader = await connection.call(proto.DELETE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error not in (proto.NONE, proto.UNKNOWN_TOPIC_OR_PARTITION):
                raise KafkaProtocolError(error, f"delete_topic {name}")
        self.leaders.pop(name, None)
