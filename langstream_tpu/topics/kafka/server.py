"""Kafka-protocol broker facade: a single-node, in-process broker that
speaks the Kafka wire protocol (the same pinned API versions as
``client.py``).

Two jobs:

1. **Test target** for the client (the reference uses an embedded Kafka
   via testcontainers, ``AbstractApplicationRunner``); here the contract
   tests run the full group/produce/fetch/commit protocol over real TCP.
2. **Compatibility endpoint**: apps (or external Kafka tooling) can point
   at this broker with any Kafka client — the Redpanda idea in miniature,
   fronting this framework's in-process log.

Storage is in-memory per topic/partition; group coordination implements
the join/sync barrier with a bounded rebalance window.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from langstream_tpu.topics.kafka import protocol as proto
from langstream_tpu.topics.kafka.protocol import Reader, Writer

logger = logging.getLogger(__name__)

# a rebalance waits for every previous member to rejoin (they notice via
# heartbeat) up to this deadline; members that miss it are evicted — the
# same role rebalance_timeout plays on a real broker
REBALANCE_DEADLINE = 10.0
FIRST_JOIN_WINDOW = 0.3  # batch-up window when the group was empty


class _Partition:
    __slots__ = ("records",)

    def __init__(self) -> None:
        # [(key, value, headers, timestamp)] — index == offset
        self.records: List[Tuple] = []


class _Group:
    def __init__(self) -> None:
        self.generation = 0
        self.members: Dict[str, bytes] = {}      # member id -> subscription
        self.leader: Optional[str] = None
        self.state = "Empty"                      # Empty|Rebalancing|Stable
        self.assignments: Dict[str, bytes] = {}
        self.offsets: Dict[Tuple[str, int], int] = {}
        self.join_barrier: Optional[asyncio.Event] = None
        self.sync_barrier: Optional[asyncio.Event] = None
        self.pending: Dict[str, bytes] = {}
        # strong ref: the loop only weakly references tasks, and a
        # GC'd close_window would strand every joiner on the barrier
        self.window_task: Optional[asyncio.Task] = None


class KafkaFacadeBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.node_id = 0
        self.topics: Dict[str, List[_Partition]] = {}
        self.groups: Dict[str, _Group] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._lock = asyncio.Lock()

    # -- lifecycle ------------------------------------------------------ #
    async def start(self) -> "KafkaFacadeBroker":
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("kafka facade broker on %s:%d", self.host, self.port)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def create_topic(self, name: str, partitions: int = 1) -> None:
        self.topics.setdefault(
            name, [_Partition() for _ in range(max(1, partitions))]
        )

    # -- connection loop ------------------------------------------------ #
    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    size_bytes = await reader.readexactly(4)
                except asyncio.IncompleteReadError:
                    return
                size = int.from_bytes(size_bytes, "big")
                payload = await reader.readexactly(size)
                request = Reader(payload)
                api_key = request.int16()
                api_version = request.int16()
                correlation_id = request.int32()
                request.string()  # client id
                try:
                    body = await self._dispatch(api_key, api_version, request)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "facade handler failed (api %d v%d)",
                        api_key, api_version,
                    )
                    return
                response = struct.pack(">i", len(body) + 4) + struct.pack(
                    ">i", correlation_id
                ) + body
                writer.write(response)
                await writer.drain()
        finally:
            writer.close()

    async def _dispatch(self, api_key: int, version: int, req: Reader) -> bytes:
        handlers = {
            proto.API_VERSIONS: self._api_versions,
            proto.METADATA: self._metadata,
            proto.PRODUCE: self._produce,
            proto.FETCH: self._fetch,
            proto.LIST_OFFSETS: self._list_offsets,
            proto.CREATE_TOPICS: self._create_topics,
            proto.DELETE_TOPICS: self._delete_topics,
            proto.FIND_COORDINATOR: self._find_coordinator,
            proto.JOIN_GROUP: self._join_group,
            proto.SYNC_GROUP: self._sync_group,
            proto.HEARTBEAT: self._heartbeat,
            proto.LEAVE_GROUP: self._leave_group,
            proto.OFFSET_COMMIT: self._offset_commit,
            proto.OFFSET_FETCH: self._offset_fetch,
        }
        handler = handlers.get(api_key)
        if handler is None:
            raise ValueError(f"unsupported api key {api_key}")
        return await handler(req)

    # -- data-plane handlers -------------------------------------------- #
    async def _api_versions(self, req: Reader) -> bytes:
        writer = Writer().int16(proto.NONE)
        supported = [
            (proto.PRODUCE, 3, 3), (proto.FETCH, 4, 4),
            (proto.LIST_OFFSETS, 1, 1), (proto.METADATA, 1, 1),
            (proto.OFFSET_COMMIT, 2, 2), (proto.OFFSET_FETCH, 1, 1),
            (proto.FIND_COORDINATOR, 0, 0), (proto.JOIN_GROUP, 1, 1),
            (proto.HEARTBEAT, 0, 0), (proto.LEAVE_GROUP, 0, 0),
            (proto.SYNC_GROUP, 0, 0), (proto.API_VERSIONS, 0, 0),
            (proto.CREATE_TOPICS, 0, 0), (proto.DELETE_TOPICS, 0, 0),
        ]
        writer.array(supported, lambda w, row: (
            w.int16(row[0]), w.int16(row[1]), w.int16(row[2]),
        ))
        return writer.build()

    async def _metadata(self, req: Reader) -> bytes:
        count = req.int32()
        names = (
            sorted(self.topics)
            if count < 0
            else [req.string() for _ in range(count)]
        )
        writer = Writer()
        writer.array([self.node_id], lambda w, node: (
            w.int32(node), w.string(self.host), w.int32(self.port),
            w.string(None),
        ))
        writer.int32(self.node_id)  # controller
        rows = []
        for name in names:
            partitions = self.topics.get(name)
            rows.append((name, partitions))
        writer.array(rows, lambda w, row: self._metadata_topic(w, row))
        return writer.build()

    def _metadata_topic(self, writer: Writer, row) -> None:
        name, partitions = row
        if partitions is None:
            writer.int16(proto.UNKNOWN_TOPIC_OR_PARTITION)
            writer.string(name)
            writer.boolean(False)
            writer.int32(0)
            return
        writer.int16(proto.NONE)
        writer.string(name)
        writer.boolean(False)
        writer.array(list(range(len(partitions))), lambda w, p: (
            w.int16(proto.NONE), w.int32(p), w.int32(self.node_id),
            w.array([self.node_id], lambda w2, r: w2.int32(r)),
            w.array([self.node_id], lambda w2, r: w2.int32(r)),
        ))

    async def _produce(self, req: Reader) -> bytes:
        req.string()  # transactional id
        req.int16()   # acks
        req.int32()   # timeout
        results = []
        async with self._lock:
            for _ in range(req.int32()):
                topic = req.string()
                for _p in range(req.int32()):
                    partition_id = req.int32()
                    record_set = req.bytes_()
                    partitions = self.topics.get(topic)
                    if partitions is None or partition_id >= len(partitions):
                        results.append((
                            topic, partition_id,
                            proto.UNKNOWN_TOPIC_OR_PARTITION, -1,
                        ))
                        continue
                    partition = partitions[partition_id]
                    base = len(partition.records)
                    for record in proto.decode_record_batches(record_set or b""):
                        partition.records.append((
                            record.key, record.value, record.headers,
                            record.timestamp,
                        ))
                    results.append((topic, partition_id, proto.NONE, base))
        writer = Writer()
        by_topic: Dict[str, List[Tuple[int, int, int]]] = {}
        for topic, partition_id, error, base in results:
            by_topic.setdefault(topic, []).append((partition_id, error, base))
        writer.array(sorted(by_topic.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, row: (
                w2.int32(row[0]), w2.int16(row[1]), w2.int64(row[2]),
                w2.int64(-1),
            )),
        ))
        writer.int32(0)  # throttle
        return writer.build()

    async def _fetch(self, req: Reader) -> bytes:
        req.int32()  # replica
        max_wait_ms = req.int32()
        min_bytes = req.int32()
        req.int32()  # max bytes
        req.int8()   # isolation
        wants: List[Tuple[str, int, int]] = []
        for _ in range(req.int32()):
            topic = req.string()
            for _p in range(req.int32()):
                partition_id = req.int32()
                offset = req.int64()
                req.int32()
                wants.append((topic, partition_id, offset))

        def collect():
            out = []
            total = 0
            for topic, partition_id, offset in wants:
                partitions = self.topics.get(topic)
                if partitions is None or partition_id >= len(partitions):
                    out.append((topic, partition_id,
                                proto.UNKNOWN_TOPIC_OR_PARTITION, 0, b""))
                    continue
                records = partitions[partition_id].records
                high_watermark = len(records)
                chunk = records[offset:offset + 500]
                encoded = b""
                if chunk:
                    encoded = proto.encode_record_batch(
                        [(k, v, h, ts) for (k, v, h, ts) in chunk],
                        base_offset=offset,
                    )
                    total += len(encoded)
                out.append((topic, partition_id, proto.NONE,
                            high_watermark, encoded))
            return out, total

        deadline = time.monotonic() + max_wait_ms / 1000.0
        while True:
            results, total = collect()
            if total >= max(1, min_bytes) or time.monotonic() >= deadline:
                break
            await asyncio.sleep(0.02)

        writer = Writer().int32(0)  # throttle
        by_topic: Dict[str, List[Tuple]] = {}
        for topic, partition_id, error, hw, encoded in results:
            by_topic.setdefault(topic, []).append(
                (partition_id, error, hw, encoded)
            )
        writer.array(sorted(by_topic.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, row: (
                w2.int32(row[0]), w2.int16(row[1]), w2.int64(row[2]),
                w2.int64(row[2]),    # last stable offset
                w2.int32(0),         # aborted txns: empty
                w2.bytes_(row[3]),
            )),
        ))
        return writer.build()

    async def _list_offsets(self, req: Reader) -> bytes:
        req.int32()
        wants: List[Tuple[str, int, int]] = []
        for _ in range(req.int32()):
            topic = req.string()
            for _p in range(req.int32()):
                wants.append((topic, req.int32(), req.int64()))
        writer = Writer()
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for topic, partition_id, timestamp in wants:
            partitions = self.topics.get(topic, [])
            end = (
                len(partitions[partition_id].records)
                if partition_id < len(partitions) else 0
            )
            offset = 0 if timestamp == -2 else end
            by_topic.setdefault(topic, []).append((partition_id, offset))
        writer.array(sorted(by_topic.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, row: (
                w2.int32(row[0]), w2.int16(proto.NONE), w2.int64(-1),
                w2.int64(row[1]),
            )),
        ))
        return writer.build()

    async def _create_topics(self, req: Reader) -> bytes:
        created: List[Tuple[str, int]] = []
        for _ in range(req.int32()):
            name = req.string()
            partitions = req.int32()
            req.int16()  # replication
            for _a in range(max(0, req.int32())):
                req.int32()
                req.array(lambda r: r.int32())
            for _c in range(max(0, req.int32())):
                req.string()
                req.string()
            if name in self.topics:
                created.append((name, proto.TOPIC_ALREADY_EXISTS))
            else:
                self.create_topic(name, partitions if partitions > 0 else 1)
                created.append((name, proto.NONE))
        req.int32()  # timeout
        writer = Writer()
        writer.array(created, lambda w, row: (
            w.string(row[0]), w.int16(row[1]),
        ))
        return writer.build()

    async def _delete_topics(self, req: Reader) -> bytes:
        names = req.array(lambda r: r.string())
        req.int32()
        writer = Writer()
        results = []
        for name in names:
            if self.topics.pop(name, None) is None:
                results.append((name, proto.UNKNOWN_TOPIC_OR_PARTITION))
            else:
                results.append((name, proto.NONE))
        writer.array(results, lambda w, row: (
            w.string(row[0]), w.int16(row[1]),
        ))
        return writer.build()

    # -- group handlers -------------------------------------------------- #
    async def _find_coordinator(self, req: Reader) -> bytes:
        req.string()
        return (
            Writer().int16(proto.NONE).int32(self.node_id)
            .string(self.host).int32(self.port).build()
        )

    async def _join_group(self, req: Reader) -> bytes:
        group_id = req.string()
        req.int32()  # session timeout
        req.int32()  # rebalance timeout
        member_id = req.string() or f"member-{uuid.uuid4().hex[:12]}"
        req.string()  # protocol type
        subscription = b""
        for _ in range(req.int32()):
            req.string()  # protocol name ("range")
            subscription = req.bytes_() or b""
        group = self.groups.setdefault(group_id, _Group())
        # enter the rebalance: collect joiners within the window
        if group.state != "Rebalancing":
            group.state = "Rebalancing"
            group.pending = {}
            group.join_barrier = asyncio.Event()
            group.sync_barrier = asyncio.Event()
            group.assignments = {}

            async def close_window(g: _Group, expected: set) -> None:
                if expected:
                    deadline = time.monotonic() + REBALANCE_DEADLINE
                    while time.monotonic() < deadline:
                        if expected <= set(g.pending):
                            break
                        await asyncio.sleep(0.01)
                else:
                    # empty group: short window so a burst of first
                    # joiners lands in one generation
                    await asyncio.sleep(FIRST_JOIN_WINDOW)
                g.generation += 1
                g.members = dict(g.pending)
                g.leader = sorted(g.members)[0] if g.members else None
                g.join_barrier.set()

            group.window_task = asyncio.get_running_loop().create_task(
                close_window(group, set(group.members))
            )
        group.pending[member_id] = subscription
        await group.join_barrier.wait()
        if member_id not in group.members:
            # joined after the window closed: next generation
            return await self._rejoin_next(group, group_id, member_id,
                                           subscription)
        writer = (
            Writer()
            .int16(proto.NONE)
            .int32(group.generation)
            .string("range")
            .string(group.leader)
            .string(member_id)
        )
        members = (
            sorted(group.members.items()) if member_id == group.leader else []
        )
        writer.array(members, lambda w, item: (
            w.string(item[0]), w.bytes_(item[1]),
        ))
        return writer.build()

    async def _rejoin_next(
        self, group: _Group, group_id: str, member_id: str, subscription: bytes
    ) -> bytes:
        return (
            Writer().int16(proto.REBALANCE_IN_PROGRESS).int32(-1)
            .string("").string("").string(member_id).int32(0).build()
        )

    async def _sync_group(self, req: Reader) -> bytes:
        group_id = req.string()
        generation = req.int32()
        member_id = req.string()
        assignments = []
        for _ in range(req.int32()):
            assignments.append((req.string(), req.bytes_() or b""))
        group = self.groups.get(group_id)
        if group is None or generation != group.generation:
            return (
                Writer().int16(proto.ILLEGAL_GENERATION).bytes_(b"").build()
            )
        if member_id == group.leader:
            group.assignments = dict(assignments)
            group.state = "Stable"
            group.sync_barrier.set()
        try:
            # bounded: a leader that died between join and sync must not
            # hang every follower — they get REBALANCE_IN_PROGRESS and
            # rejoin (which elects a live leader)
            await asyncio.wait_for(
                group.sync_barrier.wait(), REBALANCE_DEADLINE
            )
        except asyncio.TimeoutError:
            group.members.pop(group.leader, None)
            group.state = "PendingRebalance"
            return (
                Writer().int16(proto.REBALANCE_IN_PROGRESS)
                .bytes_(b"").build()
            )
        return (
            Writer().int16(proto.NONE)
            .bytes_(group.assignments.get(member_id, b"")).build()
        )

    async def _heartbeat(self, req: Reader) -> bytes:
        group_id = req.string()
        generation = req.int32()
        member_id = req.string()
        group = self.groups.get(group_id)
        if group is None or member_id not in group.members:
            return Writer().int16(proto.UNKNOWN_MEMBER_ID).build()
        if group.state in ("Rebalancing", "PendingRebalance"):
            return Writer().int16(proto.REBALANCE_IN_PROGRESS).build()
        if generation != group.generation:
            return Writer().int16(proto.ILLEGAL_GENERATION).build()
        return Writer().int16(proto.NONE).build()

    async def _leave_group(self, req: Reader) -> bytes:
        group_id = req.string()
        member_id = req.string()
        group = self.groups.get(group_id)
        if group is not None:
            group.members.pop(member_id, None)
            group.pending.pop(member_id, None)
            # survivors must rebalance to take over the partitions
            if group.members and group.state == "Stable":
                group.state = "PendingRebalance"
        return Writer().int16(proto.NONE).build()

    async def _offset_commit(self, req: Reader) -> bytes:
        group_id = req.string()
        req.int32()   # generation (trusted in the facade)
        req.string()  # member
        req.int64()   # retention
        group = self.groups.setdefault(group_id, _Group())
        results: Dict[str, List[Tuple[int, int]]] = {}
        for _ in range(req.int32()):
            topic = req.string()
            for _p in range(req.int32()):
                partition_id = req.int32()
                offset = req.int64()
                req.string()
                group.offsets[(topic, partition_id)] = offset
                results.setdefault(topic, []).append(
                    (partition_id, proto.NONE)
                )
        writer = Writer()
        writer.array(sorted(results.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, row: (
                w2.int32(row[0]), w2.int16(row[1]),
            )),
        ))
        return writer.build()

    async def _offset_fetch(self, req: Reader) -> bytes:
        group_id = req.string()
        group = self.groups.setdefault(group_id, _Group())
        wants: Dict[str, List[int]] = {}
        for _ in range(req.int32()):
            topic = req.string()
            wants[topic] = req.array(lambda r: r.int32())
        writer = Writer()
        writer.array(sorted(wants.items()), lambda w, item: (
            w.string(item[0]),
            w.array(item[1], lambda w2, partition_id: (
                w2.int32(partition_id),
                w2.int64(group.offsets.get((item[0], partition_id), -1)),
                w2.string(None),
                w2.int16(proto.NONE),
            )),
        ))
        return writer.build()


async def serve_kafka_facade(
    host: str = "127.0.0.1", port: int = 0
) -> KafkaFacadeBroker:
    return await KafkaFacadeBroker(host, port).start()
