"""In-process broker with Kafka-like semantics.

The default transport for local runs and tests. Semantics modelled on the
reference's Kafka data plane
(``langstream-kafka-runtime/src/main/java/ai/langstream/kafka/runner/``):

- Topics have N partitions; records are routed by ``hash(key) % N`` when a
  key is present, round-robin otherwise (Kafka default partitioner shape).
- Consumers join a *group*; partitions are split across group members, and a
  member joining/leaving triggers a rebalance with redelivery of uncommitted
  records (reference: ``KafkaConsumerWrapper.onPartitionsRevoked``,
  ``KafkaConsumerWrapper.java:82-111``).
- Commits may arrive out of order (async sink completions); the durable
  offset only advances over the *contiguous* prefix of acknowledged offsets
  — the reference's TreeSet watermark logic
  (``KafkaConsumerWrapper.java:52-230``).
- Readers tail a topic without a group (gateway consume path).

Everything is asyncio-native and lock-free from the caller's perspective:
one event loop, plain data structures, ``asyncio.Condition`` for blocking
polls.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Set, Tuple

from langstream_tpu.api.records import Header, Record, now_millis
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicProducer,
    TopicReader,
    TopicSpec,
)


@dataclasses.dataclass(frozen=True)
class BrokerRecord(Record):
    """A record as delivered by the broker: carries its coordinates so a
    later :meth:`MemoryTopicConsumer.commit` can locate the offset (the
    reference wraps ConsumerRecords the same way)."""

    partition: int = 0
    offset: int = -1


class _Partition:
    __slots__ = ("records", "base")

    def __init__(self) -> None:
        self.records: List[BrokerRecord] = []
        self.base = 0  # offset of records[0] (for future truncation)

    def append(self, record: Record, topic: str, partition: int) -> BrokerRecord:
        offset = self.base + len(self.records)
        stored = BrokerRecord(
            value=record.value,
            key=record.key,
            origin=topic,
            timestamp=record.timestamp or now_millis(),
            headers=record.headers,
            partition=partition,
            offset=offset,
        )
        self.records.append(stored)
        return stored

    def end_offset(self) -> int:
        return self.base + len(self.records)

    def fetch(self, start: int, limit: int) -> List[BrokerRecord]:
        idx = start - self.base
        if idx < 0:
            idx = 0
        return self.records[idx : idx + limit]


class _Topic:
    def __init__(self, spec: TopicSpec) -> None:
        self.spec = spec
        self.partitions = [_Partition() for _ in range(max(1, spec.partitions))]
        self._rr = itertools.count()

    def route(self, record: Record) -> int:
        if record.key is not None:
            key = record.key
            if isinstance(key, (dict, list)):
                key = repr(key)
            return hash(key) % len(self.partitions)
        return next(self._rr) % len(self.partitions)


class _GroupState:
    """Per consumer-group state: committed watermarks + membership."""

    def __init__(self, n_partitions: int) -> None:
        self.committed = [0] * n_partitions
        self.members: List["MemoryTopicConsumer"] = []
        self.generation = 0

    def assignment(self, member: "MemoryTopicConsumer") -> List[int]:
        if member not in self.members:
            return []
        n = len(self.members)
        i = self.members.index(member)
        return [p for p in range(len(self.committed)) if p % n == i]


class MemoryBroker:
    """One in-process broker instance (≈ one Kafka cluster)."""

    def __init__(self) -> None:
        self.topics: Dict[str, _Topic] = {}
        self.groups: Dict[Tuple[str, str], _GroupState] = {}
        self._data_available = asyncio.Condition()

    # -------------------------------------------------------------- #
    # admin
    # -------------------------------------------------------------- #
    def ensure_topic(self, name: str, partitions: int = 1) -> _Topic:
        topic = self.topics.get(name)
        if topic is None:
            topic = _Topic(TopicSpec(name=name, partitions=partitions))
            self.topics[name] = topic
        return topic

    def create_topic(self, spec: TopicSpec) -> None:
        if spec.name not in self.topics:
            self.topics[spec.name] = _Topic(spec)

    def delete_topic(self, name: str) -> None:
        self.topics.pop(name, None)
        for key in [k for k in self.groups if k[0] == name]:
            self.groups.pop(key)

    def group(self, topic: str, group_id: str) -> _GroupState:
        key = (topic, group_id)
        state = self.groups.get(key)
        topic_obj = self.ensure_topic(topic)
        if state is None:
            state = _GroupState(len(topic_obj.partitions))
            self.groups[key] = state
        return state

    # -------------------------------------------------------------- #
    # data
    # -------------------------------------------------------------- #
    async def publish(self, topic_name: str, record: Record) -> BrokerRecord:
        topic = self.ensure_topic(topic_name)
        partition = topic.route(record)
        stored = topic.partitions[partition].append(record, topic_name, partition)
        async with self._data_available:
            self._data_available.notify_all()
        return stored

    async def wait_for_data(self, timeout: float) -> None:
        try:
            async with self._data_available:
                await asyncio.wait_for(self._data_available.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def stats(self) -> Dict[str, Any]:
        return {
            name: {
                "partitions": len(t.partitions),
                "records": sum(p.end_offset() - p.base for p in t.partitions),
            }
            for name, t in self.topics.items()
        }


class MemoryTopicProducer(TopicProducer):
    def __init__(self, broker: MemoryBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic
        self._count = 0

    @property
    def topic(self) -> str:
        return self._topic

    async def write(self, record: Record) -> None:
        await self._broker.publish(self._topic, record)
        self._count += 1

    def total_in(self) -> int:
        return self._count


class MemoryTopicConsumer(TopicConsumer):
    """Group member with out-of-order ack tracking.

    Watermark logic per partition (reference
    ``KafkaConsumerWrapper.java:52-230``): ``next_fetch`` advances on read;
    ``acked`` collects out-of-order acknowledgements; ``committed`` (stored
    on the group) only advances while the next offset is in ``acked``.
    """

    def __init__(self, broker: MemoryBroker, topic: str, group_id: str) -> None:
        self._broker = broker
        self._topic = topic
        self._group_id = group_id
        self._next_fetch: Dict[int, int] = {}
        self._acked: Dict[int, Set[int]] = {}
        self._generation = -1
        self._count = 0
        self._started = False

    # -- membership ------------------------------------------------- #
    async def start(self) -> None:
        group = self._broker.group(self._topic, self._group_id)
        if self not in group.members:
            group.members.append(self)
            group.generation += 1
        self._started = True

    async def close(self) -> None:
        group = self._broker.group(self._topic, self._group_id)
        if self in group.members:
            group.members.remove(self)
            group.generation += 1
        self._started = False

    def _sync_generation(self, group: _GroupState) -> None:
        if self._generation != group.generation:
            # Rebalance: drop local fetch positions; uncommitted records will
            # be redelivered from the committed watermark (Kafka semantics).
            self._next_fetch = {}
            self._acked = {}
            self._generation = group.generation

    # -- data ------------------------------------------------------- #
    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if not self._started:
            await self.start()
        batch = self._poll(max_records)
        if batch:
            return batch
        await self._broker.wait_for_data(timeout)
        return self._poll(max_records)

    def _poll(self, max_records: int) -> List[Record]:
        group = self._broker.group(self._topic, self._group_id)
        self._sync_generation(group)
        topic = self._broker.ensure_topic(self._topic)
        out: List[Record] = []
        for partition_id in group.assignment(self):
            if len(out) >= max_records:
                break
            start = self._next_fetch.get(
                partition_id, group.committed[partition_id]
            )
            fetched = topic.partitions[partition_id].fetch(
                start, max_records - len(out)
            )
            if fetched:
                self._next_fetch[partition_id] = fetched[-1].offset + 1
                out.extend(fetched)
        self._count += len(out)
        return out

    async def commit(self, records: List[Record]) -> None:
        group = self._broker.group(self._topic, self._group_id)
        self._sync_generation(group)
        for record in records:
            if not isinstance(record, BrokerRecord):
                continue
            acked = self._acked.setdefault(record.partition, set())
            acked.add(record.offset)
            # advance the contiguous watermark
            watermark = group.committed[record.partition]
            while watermark in acked:
                acked.discard(watermark)
                watermark += 1
            group.committed[record.partition] = watermark

    def committed_offsets(self) -> List[int]:
        group = self._broker.group(self._topic, self._group_id)
        return list(group.committed)

    def total_out(self) -> int:
        return self._count


class MemoryTopicReader(TopicReader):
    def __init__(
        self,
        broker: MemoryBroker,
        topic: str,
        initial_position: OffsetPosition,
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._initial = initial_position
        self._positions: Optional[Dict[int, int]] = None

    async def start(self) -> None:
        topic = self._broker.ensure_topic(self._topic)
        if self._initial is OffsetPosition.EARLIEST:
            self._positions = {p: 0 for p in range(len(topic.partitions))}
        else:
            self._positions = {
                p: topic.partitions[p].end_offset()
                for p in range(len(topic.partitions))
            }

    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if self._positions is None:
            await self.start()
        batch = self._poll(max_records)
        if batch:
            return batch
        await self._broker.wait_for_data(timeout)
        return self._poll(max_records)

    def _poll(self, max_records: int) -> List[Record]:
        assert self._positions is not None
        topic = self._broker.ensure_topic(self._topic)
        out: List[Record] = []
        for partition_id in range(len(topic.partitions)):
            if len(out) >= max_records:
                break
            start = self._positions.setdefault(partition_id, 0)
            fetched = topic.partitions[partition_id].fetch(
                start, max_records - len(out)
            )
            if fetched:
                self._positions[partition_id] = fetched[-1].offset + 1
                out.extend(fetched)
        return out


class MemoryTopicAdmin(TopicAdmin):
    def __init__(self, broker: MemoryBroker) -> None:
        self._broker = broker

    async def create_topic(self, spec: TopicSpec) -> None:
        self._broker.create_topic(spec)

    async def delete_topic(self, name: str) -> None:
        self._broker.delete_topic(name)


class MemoryTopicConnectionsRuntime(TopicConnectionsRuntime):
    """Factory bound to one :class:`MemoryBroker`.

    By default every runtime instance owns a private broker; the local
    application runner passes one shared broker so all agents of an app see
    the same topics.
    """

    def __init__(self, broker: Optional[MemoryBroker] = None) -> None:
        self.broker = broker or MemoryBroker()

    def create_consumer(self, agent_id: str, config: Dict[str, Any]) -> TopicConsumer:
        return MemoryTopicConsumer(
            self.broker,
            topic=config["topic"],
            group_id=config.get("group", f"langstream-agent-{agent_id}"),
        )

    def create_producer(self, agent_id: str, config: Dict[str, Any]) -> TopicProducer:
        return MemoryTopicProducer(self.broker, topic=config["topic"])

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        return MemoryTopicReader(self.broker, config["topic"], initial_position)

    def create_admin(self) -> TopicAdmin:
        return MemoryTopicAdmin(self.broker)
