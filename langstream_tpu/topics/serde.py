"""Typed payload envelope shared by the external-broker runtimes.

Python record values (str / bytes / dict / list / numbers / None) must
round-trip through brokers that only carry bytes. Each payload travels
with a one-letter kind tag (``s``/``b``/``j``/``n``); foreign records
(no tag) decode as UTF-8 text, falling back to raw bytes — the same
contract the reference gets from configurable serializers.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Tuple


def encode_payload(value: Any) -> Tuple[Optional[bytes], str]:
    if value is None:
        return None, "n"
    if isinstance(value, bytes):
        return value, "b"
    if isinstance(value, str):
        return value.encode("utf-8"), "s"
    return json.dumps(value).encode("utf-8"), "j"


def decode_payload(data: Optional[bytes], kind: Optional[str]) -> Any:
    if data is None or kind == "n":
        return None
    if kind == "b":
        return data
    if kind == "j":
        return json.loads(data.decode("utf-8"))
    if kind == "s":
        return data.decode("utf-8")
    try:  # foreign record: no envelope
        return data.decode("utf-8")
    except UnicodeDecodeError:
        return data
