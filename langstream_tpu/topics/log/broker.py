"""Durable broker ("tpulog") — the framework's own Kafka-role data plane.

The reference delegates durable inter-agent transport to an external broker
(Kafka/Pulsar/Pravega, SURVEY §2.5); this framework ships its own: an
embedded, file-backed, partitioned log broker whose storage core is native
C++ (``langstream_tpu/native/logstore.cpp``) and whose consumer semantics
mirror the reference's Kafka wrapper
(``langstream-kafka-runtime/.../KafkaConsumerWrapper.java:52-230``):

- records are routed to partitions by a *stable* key hash (crc32) so that
  session affinity survives across processes and restarts;
- consumers join a group; partitions are split across members; membership
  changes bump the group generation and uncommitted records are redelivered
  from the committed watermark;
- commits may arrive out of order; the durable committed offset advances
  only over the contiguous prefix of acknowledged offsets, and is persisted
  (the reference stores this in Kafka's __consumer_offsets);
- a ``<topic>-deadletter`` producer is available for the error policies.

Run it embedded (one process owns the files) or behind the TCP server
(``langstream_tpu/topics/log/server.py``) for multi-process apps.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import pathlib
import tempfile
import threading
import urllib.parse
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicProducer,
    TopicReader,
    TopicSpec,
)
from langstream_tpu.topics.log import codec
from langstream_tpu.topics.log.store import (
    DEFAULT_SEGMENT_BYTES,
    open_partition_log,
)
from langstream_tpu.topics.memory import BrokerRecord


def stable_partition(key: Any, n_partitions: int) -> int:
    """Deterministic cross-process key -> partition routing."""
    if isinstance(key, bytes):
        data = key
    elif isinstance(key, str):
        data = key.encode("utf-8")
    else:
        data = repr(key).encode("utf-8")
    return zlib.crc32(data) % n_partitions


def _atomic_write_json(path: pathlib.Path, doc: Any) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _LogTopic:
    def __init__(self, root: pathlib.Path, spec: TopicSpec, segment_bytes: int):
        self.spec = spec
        self.dir = root / spec.name
        self.dir.mkdir(parents=True, exist_ok=True)
        meta = self.dir / "meta.json"
        if not meta.exists():
            _atomic_write_json(
                meta, {"partitions": max(1, spec.partitions)}
            )
        n = json.loads(meta.read_text())["partitions"]
        self.partitions = [
            open_partition_log(str(self.dir / f"partition-{p}"), segment_bytes)
            for p in range(n)
        ]
        self._rr = itertools.count()

    def route(self, record: Record) -> int:
        if record.key is not None:
            return stable_partition(record.key, len(self.partitions))
        return next(self._rr) % len(self.partitions)

    def close(self) -> None:
        for p in self.partitions:
            p.close()


class _LogGroupState:
    """Group membership (in-memory) + committed watermarks (persisted)."""

    def __init__(self, path: pathlib.Path, n_partitions: int):
        self.path = path
        self.members: List[Any] = []  # member tokens (consumer objects or ids)
        self.generation = 0
        if path.exists():
            stored = json.loads(path.read_text())
            self.committed = [
                int(x) for x in stored.get("committed", [0] * n_partitions)
            ]
            while len(self.committed) < n_partitions:
                self.committed.append(0)
        else:
            self.committed = [0] * n_partitions

    def persist(self) -> None:
        _atomic_write_json(self.path, {"committed": self.committed})

    def assignment(self, member: Any) -> List[int]:
        if member not in self.members:
            return []
        n = len(self.members)
        i = self.members.index(member)
        return [p for p in range(len(self.committed)) if p % n == i]


class LogBroker:
    """One durable broker instance rooted at a directory."""

    def __init__(
        self,
        root: str,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        default_partitions: int = 1,
    ) -> None:
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._segment_bytes = segment_bytes
        self._default_partitions = default_partitions
        self.topics: Dict[str, _LogTopic] = {}
        self.groups: Dict[Tuple[str, str], _LogGroupState] = {}
        self._lock = threading.Lock()
        self._data_available = asyncio.Condition()
        # recover topics already on disk
        for entry in self.root.iterdir():
            if entry.is_dir() and not entry.name.startswith("__"):
                self.ensure_topic(entry.name)

    # -- admin ------------------------------------------------------- #
    def ensure_topic(self, name: str, partitions: Optional[int] = None) -> _LogTopic:
        with self._lock:
            topic = self.topics.get(name)
            if topic is None:
                topic = _LogTopic(
                    self.root,
                    TopicSpec(
                        name=name,
                        partitions=partitions or self._default_partitions,
                    ),
                    self._segment_bytes,
                )
                self.topics[name] = topic
            return topic

    def create_topic(self, spec: TopicSpec) -> None:
        self.ensure_topic(spec.name, spec.partitions)

    def delete_topic(self, name: str) -> None:
        with self._lock:
            topic = self.topics.pop(name, None)
            if topic is not None:
                topic.close()
            for key in [k for k in self.groups if k[0] == name]:
                self.groups.pop(key)
        # retain files on disk (Kafka delete is async too); a fresh
        # create_topic with the same name resumes from the old log.

    def group(self, topic_name: str, group_id: str) -> _LogGroupState:
        with self._lock:
            key = (topic_name, group_id)
            state = self.groups.get(key)
            if state is None:
                topic = self.topics.get(topic_name)
            else:
                return state
        topic = topic or self.ensure_topic(topic_name)
        with self._lock:
            state = self.groups.get(key)
            if state is None:
                # percent-encode each part so the separator '@' (which
                # quote() always escapes) can't collide with characters
                # inside group or topic names — "a__b"/"c" and "a"/"b__c"
                # must not share a watermark file.
                safe = (
                    urllib.parse.quote(group_id, safe="")
                    + "@"
                    + urllib.parse.quote(topic_name, safe="")
                )
                state = _LogGroupState(
                    self.root / "__groups__" / f"{safe}.json",
                    len(topic.partitions),
                )
                self.groups[key] = state
            return state

    # -- data -------------------------------------------------------- #
    async def publish(self, topic_name: str, record: Record) -> BrokerRecord:
        topic = self.ensure_topic(topic_name)
        partition = topic.route(record)
        stored = BrokerRecord(
            value=record.value,
            key=record.key,
            origin=topic_name,
            timestamp=record.timestamp or now_millis(),
            headers=record.headers,
            partition=partition,
            offset=0,
        )
        payload = codec.encode_record(stored)
        offset = topic.partitions[partition].append(payload)
        stored = BrokerRecord(
            value=stored.value,
            key=stored.key,
            origin=stored.origin,
            timestamp=stored.timestamp,
            headers=stored.headers,
            partition=partition,
            offset=offset,
        )
        async with self._data_available:
            self._data_available.notify_all()
        return stored

    def fetch(
        self, topic_name: str, partition: int, start: int, max_records: int
    ) -> List[BrokerRecord]:
        topic = self.ensure_topic(topic_name)
        raw = topic.partitions[partition].read_batch(start, max_records)
        out = []
        for offset, payload in raw:
            record = codec.decode_record(payload, topic_name)
            out.append(
                BrokerRecord(
                    value=record.value,
                    key=record.key,
                    origin=topic_name,
                    timestamp=record.timestamp,
                    headers=record.headers,
                    partition=partition,
                    offset=offset,
                )
            )
        return out

    def end_offsets(self, topic_name: str) -> List[int]:
        topic = self.ensure_topic(topic_name)
        return [p.end_offset() for p in topic.partitions]

    async def wait_for_data(self, timeout: float) -> None:
        try:
            async with self._data_available:
                await asyncio.wait_for(self._data_available.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def stats(self) -> Dict[str, Any]:
        return {
            name: {
                "partitions": len(t.partitions),
                "end_offsets": [p.end_offset() for p in t.partitions],
            }
            for name, t in self.topics.items()
        }

    def close(self) -> None:
        with self._lock:
            for topic in self.topics.values():
                topic.close()
            self.topics.clear()


class LogTopicProducer(TopicProducer):
    def __init__(self, broker: LogBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic
        self._count = 0

    @property
    def topic(self) -> str:
        return self._topic

    async def write(self, record: Record) -> None:
        await self._broker.publish(self._topic, record)
        self._count += 1

    def total_in(self) -> int:
        return self._count


class LogTopicConsumer(TopicConsumer):
    """Durable group member with out-of-order ack watermarking (embedded)."""

    def __init__(self, broker: LogBroker, topic: str, group_id: str) -> None:
        self._broker = broker
        self._topic = topic
        self._group_id = group_id
        self._next_fetch: Dict[int, int] = {}
        self._acked: Dict[int, Set[int]] = {}
        self._generation = -1
        self._count = 0
        self._started = False

    async def start(self) -> None:
        group = self._broker.group(self._topic, self._group_id)
        if self not in group.members:
            group.members.append(self)
            group.generation += 1
        self._started = True

    async def close(self) -> None:
        group = self._broker.group(self._topic, self._group_id)
        if self in group.members:
            group.members.remove(self)
            group.generation += 1
        self._started = False

    def _sync_generation(self, group: _LogGroupState) -> None:
        if self._generation != group.generation:
            self._next_fetch = {}
            self._acked = {}
            self._generation = group.generation

    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if not self._started:
            await self.start()
        batch = self._poll(max_records)
        if batch:
            return batch
        await self._broker.wait_for_data(timeout)
        return self._poll(max_records)

    def _poll(self, max_records: int) -> List[Record]:
        group = self._broker.group(self._topic, self._group_id)
        self._sync_generation(group)
        out: List[Record] = []
        for partition_id in group.assignment(self):
            if len(out) >= max_records:
                break
            start = self._next_fetch.get(
                partition_id, group.committed[partition_id]
            )
            fetched = self._broker.fetch(
                self._topic, partition_id, start, max_records - len(out)
            )
            if fetched:
                self._next_fetch[partition_id] = fetched[-1].offset + 1
                out.extend(fetched)
        self._count += len(out)
        return out

    async def commit(self, records: List[Record]) -> None:
        group = self._broker.group(self._topic, self._group_id)
        self._sync_generation(group)
        dirty = False
        for record in records:
            if not isinstance(record, BrokerRecord):
                continue
            acked = self._acked.setdefault(record.partition, set())
            acked.add(record.offset)
            watermark = group.committed[record.partition]
            while watermark in acked:
                acked.discard(watermark)
                watermark += 1
            if watermark != group.committed[record.partition]:
                group.committed[record.partition] = watermark
                dirty = True
        if dirty:
            group.persist()

    def committed_offsets(self) -> List[int]:
        group = self._broker.group(self._topic, self._group_id)
        return list(group.committed)

    def total_out(self) -> int:
        return self._count


class LogTopicReader(TopicReader):
    def __init__(
        self, broker: LogBroker, topic: str, initial_position: OffsetPosition
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._initial = initial_position
        self._positions: Optional[Dict[int, int]] = None

    async def start(self) -> None:
        ends = self._broker.end_offsets(self._topic)
        if self._initial is OffsetPosition.EARLIEST:
            self._positions = {p: 0 for p in range(len(ends))}
        else:
            self._positions = dict(enumerate(ends))

    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if self._positions is None:
            await self.start()
        batch = self._poll(max_records)
        if batch:
            return batch
        await self._broker.wait_for_data(timeout)
        return self._poll(max_records)

    def _poll(self, max_records: int) -> List[Record]:
        assert self._positions is not None
        out: List[Record] = []
        for partition_id in list(self._positions):
            if len(out) >= max_records:
                break
            fetched = self._broker.fetch(
                self._topic,
                partition_id,
                self._positions[partition_id],
                max_records - len(out),
            )
            if fetched:
                self._positions[partition_id] = fetched[-1].offset + 1
                out.extend(fetched)
        return out


class LogTopicAdmin(TopicAdmin):
    def __init__(self, broker: LogBroker) -> None:
        self._broker = broker

    async def create_topic(self, spec: TopicSpec) -> None:
        self._broker.create_topic(spec)

    async def delete_topic(self, name: str) -> None:
        self._broker.delete_topic(name)


class LogTopicConnectionsRuntime(TopicConnectionsRuntime):
    """Embedded durable runtime: one process owns the broker directory.

    ``streamingCluster.configuration.directory`` selects the root. For
    multi-process apps use the served variant
    (:class:`langstream_tpu.topics.log.client.RemoteTopicConnectionsRuntime`).
    """

    def __init__(self, broker: Optional[LogBroker] = None, root: Optional[str] = None):
        if broker is None:
            broker = LogBroker(root or tempfile.mkdtemp(prefix="tpulog-"))
        self.broker = broker

    def create_consumer(self, agent_id: str, config: Dict[str, Any]) -> TopicConsumer:
        return LogTopicConsumer(
            self.broker,
            topic=config["topic"],
            group_id=config.get("group", f"langstream-agent-{agent_id}"),
        )

    def create_producer(self, agent_id: str, config: Dict[str, Any]) -> TopicProducer:
        return LogTopicProducer(self.broker, topic=config["topic"])

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        return LogTopicReader(self.broker, config["topic"], initial_position)

    def create_admin(self) -> TopicAdmin:
        return LogTopicAdmin(self.broker)

    async def init(self, streaming_cluster_config: Dict[str, Any]) -> None:
        ...

    async def close(self) -> None:
        self.broker.close()
