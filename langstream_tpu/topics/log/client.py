"""Client runtime for the served tpulog broker.

``RemoteTopicConnectionsRuntime`` implements the broker-portable topic SPI
(``langstream_tpu/api/topics.py``) over the TCP protocol of
``langstream_tpu/topics/log/server.py`` — the moral equivalent of the
reference's Kafka client wrappers
(``langstream-kafka-runtime/.../KafkaTopicConnectionsRuntime.java:53``).

Configured from ``streamingCluster`` YAML as::

    streamingCluster:
      type: tpulog
      configuration:
        address: "127.0.0.1:4551"

Each consumer/producer/reader owns its own connection (one in-flight
request per connection; the server is happy with many connections).
"""

from __future__ import annotations

import asyncio
import json
import struct
import uuid
from typing import Any, Dict, List, Optional, Set

from langstream_tpu.api.records import Record
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicProducer,
    TopicReader,
    TopicSpec,
)
from langstream_tpu.topics.log import codec
from langstream_tpu.topics.memory import BrokerRecord

_LEN = struct.Struct("<I")


class BrokerConnection:
    def __init__(self, host: str, port: int) -> None:
        self._host = host
        self._port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _ensure(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self._host, self._port
            )

    async def request(self, message: Dict[str, Any]) -> Dict[str, Any]:
        async with self._lock:
            await self._ensure()
            assert self._reader is not None and self._writer is not None
            payload = json.dumps(message, default=str).encode()
            try:
                self._writer.write(_LEN.pack(len(payload)) + payload)
                await self._writer.drain()
                header = await self._reader.readexactly(_LEN.size)
                (length,) = _LEN.unpack(header)
                body = await self._reader.readexactly(length)
            except (OSError, asyncio.IncompleteReadError):
                # A clean broker FIN leaves the transport half-open
                # (is_closing() stays False), so _ensure would keep
                # reusing the dead socket — drop it so the next request
                # reconnects.
                self._writer.close()
                self._writer = None
                self._reader = None
                raise
        response = json.loads(body)
        if not response.get("ok"):
            raise RuntimeError(
                f"broker error for op {message.get('op')!r}: "
                f"{response.get('error')}"
            )
        return response

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None


def _parse_address(address: str) -> tuple:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


class RemoteTopicProducer(TopicProducer):
    def __init__(self, conn: BrokerConnection, topic: str) -> None:
        self._conn = conn
        self._topic = topic
        self._count = 0

    @property
    def topic(self) -> str:
        return self._topic

    async def write(self, record: Record) -> None:
        await self._conn.request(
            {
                "op": "produce",
                "topic": self._topic,
                "record": codec.record_to_json(record),
            }
        )
        self._count += 1

    def total_in(self) -> int:
        return self._count

    async def close(self) -> None:
        await self._conn.close()


class RemoteTopicConsumer(TopicConsumer):
    """Group member against the served broker.

    The server owns membership + the commit watermark; the client tracks
    fetch positions per assigned partition, resetting to the committed
    watermark whenever the group generation changes (rebalance redelivery).
    """

    def __init__(
        self, conn: BrokerConnection, topic: str, group_id: str
    ) -> None:
        self._conn = conn
        self._topic = topic
        self._group = group_id
        self._member = uuid.uuid4().hex
        self._generation = -1
        self._assignment: List[int] = []
        self._next_fetch: Dict[int, int] = {}
        self._pending_acks: Dict[int, Set[int]] = {}
        self._count = 0
        self._started = False

    async def start(self) -> None:
        response = await self._conn.request(
            {
                "op": "join",
                "topic": self._topic,
                "group": self._group,
                "member": self._member,
            }
        )
        self._apply_poll(response)
        self._started = True

    async def close(self) -> None:
        if self._started:
            try:
                await self._conn.request(
                    {
                        "op": "leave",
                        "topic": self._topic,
                        "group": self._group,
                        "member": self._member,
                    }
                )
            except (RuntimeError, OSError, asyncio.IncompleteReadError):
                pass
        await self._conn.close()
        self._started = False

    def _apply_poll(self, response: Dict[str, Any]) -> None:
        generation = response["generation"]
        if generation != self._generation:
            self._generation = generation
            self._assignment = list(response["assignment"])
            committed = response["committed"]
            self._next_fetch = {
                p: committed[p] for p in self._assignment
            }

    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if not self._started:
            await self.start()
        response = await self._conn.request(
            {
                "op": "poll",
                "topic": self._topic,
                "group": self._group,
                "member": self._member,
            }
        )
        self._apply_poll(response)
        if not self._assignment:
            await asyncio.sleep(min(timeout, 0.05))
            return []
        response = await self._conn.request(
            {
                "op": "fetch",
                "topic": self._topic,
                "positions": {
                    str(p): self._next_fetch.get(p, 0) for p in self._assignment
                },
                "max_records": max_records,
                "timeout": timeout,
            }
        )
        records = [codec.record_from_json(doc) for doc in response["records"]]
        for record in records:
            assert isinstance(record, BrokerRecord)
            self._next_fetch[record.partition] = record.offset + 1
        self._count += len(records)
        return records

    async def commit(self, records: List[Record]) -> None:
        offsets: Dict[str, List[int]] = {}
        for record in records:
            if isinstance(record, BrokerRecord):
                offsets.setdefault(str(record.partition), []).append(
                    record.offset
                )
        if not offsets:
            return
        await self._conn.request(
            {
                "op": "commit",
                "topic": self._topic,
                "group": self._group,
                "member": self._member,
                "offsets": offsets,
            }
        )

    def total_out(self) -> int:
        return self._count


class RemoteTopicReader(TopicReader):
    def __init__(
        self,
        conn: BrokerConnection,
        topic: str,
        initial_position: OffsetPosition,
    ) -> None:
        self._conn = conn
        self._topic = topic
        self._initial = initial_position
        self._positions: Optional[Dict[int, int]] = None

    async def start(self) -> None:
        response = await self._conn.request(
            {"op": "end_offsets", "topic": self._topic}
        )
        ends = response["ends"]
        if self._initial is OffsetPosition.EARLIEST:
            self._positions = {p: 0 for p in range(len(ends))}
        else:
            self._positions = dict(enumerate(ends))

    async def read(self, max_records: int = 100, timeout: float = 0.1) -> List[Record]:
        if self._positions is None:
            await self.start()
        assert self._positions is not None
        response = await self._conn.request(
            {
                "op": "fetch",
                "topic": self._topic,
                "positions": {str(p): s for p, s in self._positions.items()},
                "max_records": max_records,
                "timeout": timeout,
            }
        )
        records = [codec.record_from_json(doc) for doc in response["records"]]
        for record in records:
            assert isinstance(record, BrokerRecord)
            self._positions[record.partition] = record.offset + 1
        return records

    async def close(self) -> None:
        await self._conn.close()


class RemoteTopicAdmin(TopicAdmin):
    def __init__(self, conn: BrokerConnection) -> None:
        self._conn = conn

    async def create_topic(self, spec: TopicSpec) -> None:
        await self._conn.request(
            {
                "op": "create_topic",
                "spec": {"name": spec.name, "partitions": spec.partitions},
            }
        )

    async def delete_topic(self, name: str) -> None:
        await self._conn.request({"op": "delete_topic", "topic": name})

    async def close(self) -> None:
        await self._conn.close()


class RemoteTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self, address: str = "127.0.0.1:4551") -> None:
        self._host, self._port = _parse_address(address)

    def _connect(self) -> BrokerConnection:
        return BrokerConnection(self._host, self._port)

    def create_consumer(self, agent_id: str, config: Dict[str, Any]) -> TopicConsumer:
        return RemoteTopicConsumer(
            self._connect(),
            topic=config["topic"],
            group_id=config.get("group", f"langstream-agent-{agent_id}"),
        )

    def create_producer(self, agent_id: str, config: Dict[str, Any]) -> TopicProducer:
        return RemoteTopicProducer(self._connect(), topic=config["topic"])

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        return RemoteTopicReader(
            self._connect(), config["topic"], initial_position
        )

    def create_admin(self) -> TopicAdmin:
        return RemoteTopicAdmin(self._connect())

    async def init(self, streaming_cluster_config: Dict[str, Any]) -> None:
        address = streaming_cluster_config.get("address")
        if address:
            self._host, self._port = _parse_address(address)
