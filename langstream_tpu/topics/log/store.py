"""Partition log store: Python facade over the native C++ segmented log.

One :class:`PartitionLog` = one topic partition on disk. The native library
(``langstream_tpu/native/logstore.cpp``) owns the file format (framed +
crc32-checked segments with an O(1) offset index); :class:`_PyPartitionLog`
is a pure-Python implementation of the *same on-disk format* used when the
toolchain is unavailable, so data written by either is readable by both.
"""

from __future__ import annotations

import ctypes
import pathlib
import struct
import threading
import zlib
from typing import List, Optional, Tuple

from langstream_tpu import native

_FRAME = struct.Struct("<II")  # len, crc32
_IDX = struct.Struct("<Q")  # file position

DEFAULT_SEGMENT_BYTES = 64 << 20


class _NativePartitionLog:
    def __init__(self, lib: ctypes.CDLL, directory: str, segment_bytes: int):
        self._lib = lib
        self._handle = lib.ls_open(directory.encode(), segment_bytes)
        if not self._handle:
            raise OSError(f"cannot open log store at {directory}")
        self._read_buf = ctypes.create_string_buffer(1 << 20)

    def append(self, payload: bytes) -> int:
        offset = self._lib.ls_append(self._handle, payload, len(payload))
        if offset < 0:
            raise OSError("log append failed")
        return offset

    def end_offset(self) -> int:
        return self._lib.ls_end_offset(self._handle)

    def read_batch(self, start: int, max_records: int) -> List[Tuple[int, bytes]]:
        while True:
            used = ctypes.c_uint64(0)
            n = self._lib.ls_read_batch(
                self._handle,
                start,
                max_records,
                self._read_buf,
                len(self._read_buf),
                ctypes.byref(used),
            )
            if n == -2:  # first record larger than the buffer: grow and retry
                self._read_buf = ctypes.create_string_buffer(
                    len(self._read_buf) * 4
                )
                continue
            break
        out: List[Tuple[int, bytes]] = []
        data = self._read_buf.raw[: used.value]
        pos = 0
        for i in range(n):
            (length,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append((start + i, data[pos : pos + length]))
            pos += length
        return out

    def sync(self) -> None:
        self._lib.ls_sync(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.ls_close(self._handle)
            self._handle = None


class _PyPartitionLog:
    """Pure-Python fallback writing the identical segment/index format."""

    def __init__(self, directory: str, segment_bytes: int):
        self._dir = pathlib.Path(directory)
        self._dir.mkdir(parents=True, exist_ok=True)
        self._segment_bytes = segment_bytes
        self._lock = threading.Lock()
        self._segments: List[Tuple[int, int]] = []  # (base, count)
        self._recover()

    def _paths(self, base: int) -> Tuple[pathlib.Path, pathlib.Path]:
        return (
            self._dir / f"{base:020d}.log",
            self._dir / f"{base:020d}.idx",
        )

    def _recover(self) -> None:
        bases = sorted(
            int(p.stem) for p in self._dir.glob("*.log") if p.stem.isdigit()
        )
        if not bases:
            bases = [0]
            for path in self._paths(0):
                path.touch()
        self._segments = []
        for base in bases:
            log_path, idx_path = self._paths(base)
            idx = idx_path.read_bytes() if idx_path.exists() else b""
            log = log_path.read_bytes() if log_path.exists() else b""
            n = len(idx) // _IDX.size
            valid = 0
            for i in range(n - 1, -1, -1):
                (pos,) = _IDX.unpack_from(idx, i * _IDX.size)
                if pos + _FRAME.size > len(log):
                    continue
                length, crc = _FRAME.unpack_from(log, pos)
                payload = log[pos + _FRAME.size : pos + _FRAME.size + length]
                if len(payload) == length and zlib.crc32(payload) == crc:
                    valid = i + 1
                    break
            # truncate torn tails
            with open(idx_path, "ab") as f:
                f.truncate(valid * _IDX.size)
            end = 0
            if valid:
                (pos,) = _IDX.unpack_from(idx, (valid - 1) * _IDX.size)
                length, _ = _FRAME.unpack_from(log, pos)
                end = pos + _FRAME.size + length
            with open(log_path, "ab") as f:
                f.truncate(end)
            self._segments.append((base, valid))

    def append(self, payload: bytes) -> int:
        with self._lock:
            base, count = self._segments[-1]
            log_path, idx_path = self._paths(base)
            size = log_path.stat().st_size if log_path.exists() else 0
            if size > 0 and size + _FRAME.size + len(payload) > self._segment_bytes:
                base, count = base + count, 0
                self._segments.append((base, 0))
                log_path, idx_path = self._paths(base)
                size = 0
            with open(log_path, "ab") as lf, open(idx_path, "ab") as xf:
                lf.write(_FRAME.pack(len(payload), zlib.crc32(payload)))
                lf.write(payload)
                xf.write(_IDX.pack(size))
            self._segments[-1] = (base, count + 1)
            return base + count

    def end_offset(self) -> int:
        with self._lock:
            base, count = self._segments[-1]
            return base + count

    def read_batch(self, start: int, max_records: int) -> List[Tuple[int, bytes]]:
        with self._lock:
            out: List[Tuple[int, bytes]] = []
            for base, count in self._segments:
                if start >= base + count or len(out) >= max_records:
                    continue
                if start < base:
                    start = base
                first = start - base
                upto = min(count, first + (max_records - len(out)))
                log_path, idx_path = self._paths(base)
                # seek-read only the needed span — that's what the .idx
                # position index is for; reading whole (up to 64 MB)
                # segments per poll would swamp the consumer loop.
                with open(idx_path, "rb") as xf:
                    xf.seek(first * _IDX.size)
                    idx = xf.read((upto - first + 1) * _IDX.size)
                (first_pos,) = _IDX.unpack_from(idx, 0)
                if upto < count:
                    (end_pos,) = _IDX.unpack_from(idx, (upto - first) * _IDX.size)
                else:
                    end_pos = log_path.stat().st_size
                with open(log_path, "rb") as lf:
                    lf.seek(first_pos)
                    log = lf.read(end_pos - first_pos)
                pos = 0
                while start < base + upto:
                    length, _ = _FRAME.unpack_from(log, pos)
                    out.append(
                        (start, log[pos + _FRAME.size : pos + _FRAME.size + length])
                    )
                    pos += _FRAME.size + length
                    start += 1
            return out

    def sync(self) -> None:
        ...

    def close(self) -> None:
        ...


def open_partition_log(
    directory: str, segment_bytes: int = DEFAULT_SEGMENT_BYTES
):
    """Open (creating/recovering) a partition log, native when possible."""
    lib = native.load_logstore()
    if lib is not None:
        return _NativePartitionLog(lib, directory, segment_bytes)
    return _PyPartitionLog(directory, segment_bytes)
