"""Record <-> bytes codec for the durable log runtime.

JSON envelope with a type-tagged escape for binary values (the reference's
Kafka plane delegates this to pluggable serializers + an Avro schema
registry; here dict/list values already carry their structure, so a
self-describing JSON envelope is the portable choice).
"""

from __future__ import annotations

import json
from typing import Any

from langstream_tpu.api.records import Record

# shared escape-aware codec: a literal user dict {"__b64__": "x"} now
# survives the round trip ({"__esc__": …} wrapping) instead of decoding
# as bytes; values written by older builds decode identically
from langstream_tpu.utils.wire_json import (  # noqa: E402
    decode_value as _decode_value,
    encode_value as _encode_value,
)


def encode_record(record: Record) -> bytes:
    doc = {
        "v": _encode_value(record.value),
        "k": _encode_value(record.key),
        "t": record.timestamp,
        "h": [[k, _encode_value(v)] for k, v in record.headers],
    }
    return json.dumps(doc, ensure_ascii=False, default=str).encode("utf-8")


def decode_record(payload: bytes, origin: str) -> Record:
    doc = json.loads(payload.decode("utf-8"))
    return Record(
        value=_decode_value(doc.get("v")),
        key=_decode_value(doc.get("k")),
        origin=origin,
        timestamp=doc.get("t"),
        headers=tuple((k, _decode_value(v)) for k, v in doc.get("h", [])),
    )


def record_to_json(record: Record) -> dict:
    """JSON-safe dict form for the wire protocol (server <-> client)."""
    doc = {
        "v": _encode_value(record.value),
        "k": _encode_value(record.key),
        "t": record.timestamp,
        "o": record.origin,
        "h": [[k, _encode_value(v)] for k, v in record.headers],
    }
    partition = getattr(record, "partition", None)
    offset = getattr(record, "offset", None)
    if partition is not None:
        doc["p"] = partition
    if offset is not None:
        doc["off"] = offset
    return doc


def record_from_json(doc: dict) -> Record:
    from langstream_tpu.topics.memory import BrokerRecord

    common = dict(
        value=_decode_value(doc.get("v")),
        key=_decode_value(doc.get("k")),
        origin=doc.get("o"),
        timestamp=doc.get("t"),
        headers=tuple((k, _decode_value(v)) for k, v in doc.get("h", [])),
    )
    if "off" in doc:
        return BrokerRecord(
            partition=doc.get("p", 0), offset=doc["off"], **common
        )
    return Record(**common)
