"""TCP broker server: multi-process access to a :class:`LogBroker`.

This is the standalone face of the tpulog broker — what Kafka's network
layer is to its log layer. One process runs ``BrokerServer`` (or
``python -m langstream_tpu broker``); agent-runner processes connect with
:class:`langstream_tpu.topics.log.client.RemoteTopicConnectionsRuntime`.

Protocol: 4-byte little-endian length prefix + JSON request/response, one
in-flight request per connection (clients pipeline by opening extra
connections). Consumer-group coordination is server-side:

- ``join``/``leave``/``poll`` manage membership; every request from a
  member doubles as a heartbeat, and members silent for longer than
  ``session_timeout`` are evicted, bumping the group generation
  (reference semantics: Kafka group coordinator + the rebalance listener in
  ``KafkaConsumerWrapper.java:82-111``).
- ``commit`` sends acknowledged offsets; the server advances the durable
  contiguous watermark per partition.
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
from typing import Any, Dict, Optional, Set, Tuple

from langstream_tpu.api.topics import TopicSpec
from langstream_tpu.topics.log import codec
from langstream_tpu.topics.log.broker import LogBroker

_LEN = struct.Struct("<I")
MAX_FRAME = 256 << 20


class _ServedGroup:
    """Server-side view of one (topic, group): members + ack sets."""

    def __init__(self) -> None:
        self.last_seen: Dict[str, float] = {}  # member_id -> monotonic ts
        self.acked: Dict[int, Set[int]] = {}

    def touch(self, member_id: str) -> None:
        self.last_seen[member_id] = time.monotonic()

    def evict_expired(self, session_timeout: float) -> bool:
        deadline = time.monotonic() - session_timeout
        expired = [m for m, ts in self.last_seen.items() if ts < deadline]
        for member in expired:
            del self.last_seen[member]
        return bool(expired)

    def members(self) -> list:
        return sorted(self.last_seen)


class BrokerServer:
    def __init__(
        self,
        broker: LogBroker,
        host: str = "127.0.0.1",
        port: int = 0,
        session_timeout: float = 15.0,
    ) -> None:
        self.broker = broker
        self.host = host
        self.port = port
        self.session_timeout = session_timeout
        self._served: Dict[Tuple[str, str], _ServedGroup] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._connections: Set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------- #
    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            for writer in list(self._connections):
                writer.close()
            await self._server.wait_closed()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- group coordination ------------------------------------------- #
    def _group_pair(self, topic: str, group_id: str):
        state = self.broker.group(topic, group_id)
        served = self._served.setdefault((topic, group_id), _ServedGroup())
        if served.evict_expired(self.session_timeout):
            state.members = served.members()
            state.generation += 1
        return state, served

    def _member_assignment(self, state, member_id: str) -> list:
        members = state.members
        if member_id not in members:
            return []
        n = len(members)
        i = members.index(member_id)
        return [p for p in range(len(state.committed)) if p % n == i]

    # -- request handling ---------------------------------------------- #
    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._connections.add(writer)
        try:
            while True:
                try:
                    header = await reader.readexactly(_LEN.size)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                (length,) = _LEN.unpack(header)
                if length > MAX_FRAME:
                    break
                body = await reader.readexactly(length)
                request = json.loads(body)
                try:
                    response = await self._dispatch(request)
                except Exception as err:  # surface to the client
                    response = {"ok": False, "error": f"{type(err).__name__}: {err}"}
                payload = json.dumps(response, default=str).encode()
                writer.write(_LEN.pack(len(payload)) + payload)
                await writer.drain()
        finally:
            self._connections.discard(writer)
            writer.close()

    async def _dispatch(self, request: Dict[str, Any]) -> Dict[str, Any]:
        op = request.get("op")
        if op == "produce":
            stored = await self.broker.publish(
                request["topic"], codec.record_from_json(request["record"])
            )
            return {"ok": True, "partition": stored.partition, "offset": stored.offset}
        if op == "fetch":
            return await self._fetch(request)
        if op == "end_offsets":
            return {"ok": True, "ends": self.broker.end_offsets(request["topic"])}
        if op == "join":
            state, served = self._group_pair(request["topic"], request["group"])
            member = request["member"]
            if member not in served.last_seen:
                served.touch(member)
                state.members = served.members()
                state.generation += 1
            else:
                served.touch(member)
            return self._poll_response(state, member)
        if op == "leave":
            state, served = self._group_pair(request["topic"], request["group"])
            if request["member"] in served.last_seen:
                del served.last_seen[request["member"]]
                state.members = served.members()
                state.generation += 1
            return {"ok": True}
        if op == "poll":
            state, served = self._group_pair(request["topic"], request["group"])
            served.touch(request["member"])
            return self._poll_response(state, request["member"])
        if op == "commit":
            return self._commit(request)
        if op == "create_topic":
            spec = request["spec"]
            self.broker.create_topic(
                TopicSpec(
                    name=spec["name"], partitions=spec.get("partitions", 1)
                )
            )
            return {"ok": True}
        if op == "delete_topic":
            self.broker.delete_topic(request["topic"])
            return {"ok": True}
        if op == "stats":
            return {"ok": True, "stats": self.broker.stats()}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _poll_response(self, state, member: str) -> Dict[str, Any]:
        return {
            "ok": True,
            "generation": state.generation,
            "assignment": self._member_assignment(state, member),
            "committed": list(state.committed),
        }

    async def _fetch(self, request: Dict[str, Any]) -> Dict[str, Any]:
        topic = request["topic"]
        partitions: Dict[int, int] = {
            int(p): int(start) for p, start in request["positions"].items()
        }
        max_records = int(request.get("max_records", 100))
        timeout = float(request.get("timeout", 0.1))
        deadline = time.monotonic() + timeout
        while True:
            records = []
            for partition, start in partitions.items():
                if len(records) >= max_records:
                    break
                records.extend(
                    self.broker.fetch(
                        topic, partition, start, max_records - len(records)
                    )
                )
            if records or time.monotonic() >= deadline:
                return {
                    "ok": True,
                    "records": [codec.record_to_json(r) for r in records],
                }
            await self.broker.wait_for_data(deadline - time.monotonic())

    def _commit(self, request: Dict[str, Any]) -> Dict[str, Any]:
        state, served = self._group_pair(request["topic"], request["group"])
        served.touch(request.get("member", ""))
        dirty = False
        for partition_str, offsets in request["offsets"].items():
            partition = int(partition_str)
            acked = served.acked.setdefault(partition, set())
            acked.update(int(o) for o in offsets)
            watermark = state.committed[partition]
            while watermark in acked:
                acked.discard(watermark)
                watermark += 1
            if watermark != state.committed[partition]:
                state.committed[partition] = watermark
                dirty = True
        if dirty:
            state.persist()
        return {"ok": True, "committed": list(state.committed)}


async def serve(
    root: str, host: str = "127.0.0.1", port: int = 4551
) -> BrokerServer:
    server = BrokerServer(LogBroker(root), host=host, port=port)
    await server.start()
    return server
