"""tpulog: the framework's durable, partitioned log broker.

- ``store`` — native (C++) segmented log files, crc-checked, O(1) index.
- ``broker`` — embedded durable broker with consumer groups and persisted
  out-of-order-commit watermarks.
- ``server``/``client`` — TCP network layer for multi-process apps.
"""

from langstream_tpu.topics.log.broker import (
    LogBroker,
    LogTopicConnectionsRuntime,
)
from langstream_tpu.topics.log.client import RemoteTopicConnectionsRuntime
from langstream_tpu.topics.log.server import BrokerServer

__all__ = [
    "LogBroker",
    "LogTopicConnectionsRuntime",
    "RemoteTopicConnectionsRuntime",
    "BrokerServer",
]
